"""Round-2 xfer-library completion (VERDICT item 7): the four missing
built-in substitution families, each verified to rewrite correctly AND
round-trip numerically (rewritten graph == original graph outputs).

Reference: create_replicate_attention_reduce (substitution.cc:3197),
create_partition_attention_combine (:3169), create_partition_concat_combine
(:3380), leading_relu_branch_combine/partition (:3464+, registered
:1839-1842).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import FFConfig, SGDOptimizer
from flexflow_tpu.core.types import ActiMode, CompMode, OpType
from flexflow_tpu.model import FFModel
from flexflow_tpu.ops.parallel_ops import CombineParams, RepartitionParams
from flexflow_tpu.search.substitution import (
    create_partition_attention_combine,
    create_partition_concat_combine,
    create_replicate_attention_reduce,
    generate_all_pcg_xfers,
    leading_relu_branch_combine,
    leading_relu_branch_partition,
)


def _predict(model, x):
    model.compile(comp_mode=CompMode.INFERENCE)
    return model.executor, np.asarray(model.executor.predict([jnp.asarray(x)])[0])


def _repredict_with_params(model, src_ex, x):
    """Re-compile after a graph rewrite, porting params of surviving guids."""
    model.executor = None
    model.compile(comp_mode=CompMode.INFERENCE)
    ex = model.executor
    for k in list(ex.params):
        if k in src_ex.params:
            ex.params[k] = src_ex.params[k]
    return np.asarray(ex.predict([jnp.asarray(x)])[0])


def _attention_model():
    config = FFConfig(batch_size=4, workers_per_node=1)
    m = FFModel(config)
    x = m.create_tensor((4, 8, 16), name="x")
    t = m.multihead_attention(x, x, x, 16, 4, name="attn")
    m.dense(t, 16, name="out")
    return m


def test_replicate_attention_reduce_roundtrip():
    m = _attention_model()
    rs = np.random.RandomState(0)
    x = rs.randn(4, 8, 16).astype(np.float32)
    ex1, want = _predict(m, x)
    xfer = create_replicate_attention_reduce(2)
    matches = xfer.find_matches(m.graph)
    assert matches
    ng = xfer.apply(m.graph, matches[0])
    assert ng is not None
    types = [n.op_type for n in ng.nodes.values()]
    assert types.count(OpType.REPLICATE) == 3 and OpType.REDUCTION in types
    m.graph = ng
    got = _repredict_with_params(m, ex1, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_partition_attention_combine_roundtrip():
    m = _attention_model()
    rs = np.random.RandomState(1)
    x = rs.randn(4, 8, 16).astype(np.float32)
    ex1, want = _predict(m, x)
    xfer = create_partition_attention_combine(2)
    matches = xfer.find_matches(m.graph)
    assert matches
    ng = xfer.apply(m.graph, matches[0])
    assert ng is not None
    types = [n.op_type for n in ng.nodes.values()]
    assert types.count(OpType.REPARTITION) == 3 and OpType.COMBINE in types
    m.graph = ng
    got = _repredict_with_params(m, ex1, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_partition_concat_combine_roundtrip():
    config = FFConfig(batch_size=4, workers_per_node=1)
    m = FFModel(config)
    x = m.create_tensor((4, 8), name="x")
    a = m.dense(x, 8, name="a")
    b = m.dense(x, 8, name="b")
    t = m.concat([a, b], axis=1, name="cat")
    m.dense(t, 4, name="out")
    rs = np.random.RandomState(2)
    xv = rs.randn(4, 8).astype(np.float32)
    ex1, want = _predict(m, xv)
    xfer = create_partition_concat_combine(2)
    matches = xfer.find_matches(m.graph)
    assert matches
    ng = xfer.apply(m.graph, matches[0])
    assert ng is not None
    types = [n.op_type for n in ng.nodes.values()]
    assert types.count(OpType.REPARTITION) == 2 and OpType.COMBINE in types
    m.graph = ng
    got = _repredict_with_params(m, ex1, xv)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_partition_concat_combine_rejects_concat_axis_0():
    config = FFConfig(batch_size=4, workers_per_node=1)
    m = FFModel(config)
    x = m.create_tensor((4, 8), name="x")
    a = m.dense(x, 8, name="a")
    b = m.dense(x, 8, name="b")
    t = m.concat([a, b], axis=0, name="cat0")
    m.dense(t, 4, name="out")
    xfer = create_partition_concat_combine(2)
    for match in xfer.find_matches(m.graph):
        assert xfer.apply(m.graph, match) is None  # partition dim == concat axis


def _branching_parallel_graph():
    """x -> relu -> {Repartition -> dense_p, Combine -> dense_a, Combine -> dense_b}."""
    config = FFConfig(batch_size=4, workers_per_node=1)
    m = FFModel(config)
    x = m.create_tensor((4, 8), name="x")
    t = m.dense(x, 8, ActiMode.RELU, name="lead")
    g = m.graph
    lead = next(n for n in g.topo_order() if n.name == "lead")
    part = g.new_node(OpType.REPARTITION, RepartitionParams(dim=0, degree=2), "part")
    c1 = g.new_node(OpType.COMBINE, CombineParams(dim=0, degree=2), "c1")
    c2 = g.new_node(OpType.COMBINE, CombineParams(dim=0, degree=2), "c2")
    for nd in (part, c1, c2):
        g.add_edge(lead, nd)
    from flexflow_tpu.core.tensor import TensorSpec
    from flexflow_tpu.model import Tensor
    from flexflow_tpu.core.types import DataType

    outs = []
    for i, nd in enumerate((part, c1, c2)):
        tt = Tensor(m, nd, 0, TensorSpec((4, 8), DataType.FLOAT))
        outs.append(m.dense(tt, 4, name=f"head{i}"))
    return m, outs


def test_leading_relu_branch_combine_rewrite_and_numerics():
    m, outs = _branching_parallel_graph()
    rs = np.random.RandomState(3)
    xv = rs.randn(4, 8).astype(np.float32)
    m.compile(comp_mode=CompMode.INFERENCE, outputs=outs)
    ex1 = m.executor
    want = [np.asarray(o) for o in ex1.predict([jnp.asarray(xv)])]
    xfer = leading_relu_branch_combine(2, num_combines=2)
    matches = xfer.find_matches(m.graph)
    assert matches
    ng = xfer.apply(m.graph, matches[0])
    assert ng is not None
    types = [n.op_type for n in ng.nodes.values()]
    assert OpType.COMBINE not in types  # combines became noops
    assert types.count(OpType.NOOP) == 2
    m.graph = ng
    m.executor = None
    m.compile(comp_mode=CompMode.INFERENCE, outputs=outs)
    for k in list(m.executor.params):
        if k in ex1.params:
            m.executor.params[k] = ex1.params[k]
    got = [np.asarray(o) for o in m.executor.predict([jnp.asarray(xv)])]
    for g_, w in zip(got, want):
        np.testing.assert_allclose(g_, w, rtol=1e-5, atol=1e-6)


def test_leading_relu_branch_partition_dedupes():
    config = FFConfig(batch_size=4, workers_per_node=1)
    m = FFModel(config)
    x = m.create_tensor((4, 8), name="x")
    t = m.dense(x, 8, ActiMode.RELU, name="lead")
    g = m.graph
    lead = next(n for n in g.topo_order() if n.name == "lead")
    p1 = g.new_node(OpType.REPARTITION, RepartitionParams(dim=0, degree=2), "p1")
    p2 = g.new_node(OpType.REPARTITION, RepartitionParams(dim=0, degree=2), "p2")
    g.add_edge(lead, p1)
    g.add_edge(lead, p2)
    from flexflow_tpu.core.tensor import TensorSpec
    from flexflow_tpu.core.types import DataType
    from flexflow_tpu.model import Tensor

    outs = [
        m.dense(Tensor(m, nd, 0, TensorSpec((4, 8), DataType.FLOAT)), 4, name=f"h{i}")
        for i, nd in enumerate((p1, p2))
    ]
    xfer = leading_relu_branch_partition(2, num_partitions=2)
    matches = xfer.find_matches(m.graph)
    assert matches
    ng = xfer.apply(m.graph, matches[0])
    assert ng is not None
    types = [n.op_type for n in ng.nodes.values()]
    assert types.count(OpType.REPARTITION) == 1
    assert types.count(OpType.NOOP) == 1
    ng.topo_order()  # acyclic


def test_generate_all_includes_new_families():
    xfers = generate_all_pcg_xfers([2, 4], enable_parameter_parallel=True)
    names = [x.name for x in xfers]
    for want in (
        "replicate_attention_reduce_2",
        "partition_attention_combine_2",
        "partition_concat_combine_2_2",
        "leading_relu_branch_combine_2_2",
        "leading_relu_branch_partition_2_2",
        "partition_softmax_combine_2_d0",
    ):
        assert any(want in n for n in names), (want, names)
