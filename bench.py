"""Benchmark driver: BERT training throughput, searched strategy vs data-parallel.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
The reference's headline is searched-strategy vs data-parallel on identical
hardware (scripts/osdi22ae/bert.sh); we report both MFUs.  vs_baseline is
the searched MFU relative to the 45%-MFU north star from BASELINE.json.

Resilience (round-1 failure mode: the tunneled 'axon' TPU backend errored
at init and the bench died with no JSON, BENCH_r01.json rc=1): the parent
process re-execs the actual benchmark as a child with retry + backoff; if
the TPU never comes up it falls back to CPU so a parseable JSON line is
always produced.

Peak FLOPs are derived from the detected chip (device_kind), not
hardcoded (round-1 weakness: v5e 197e12 was assumed).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_CHILD_ENV = "_FF_BENCH_CHILD"

# (device_kind substring, peak bf16 FLOP/s per jax device), most specific first.
# v2/v3 expose one core per jax device; v4+ one (mega)chip per device.
_PEAK_BF16 = [
    ("v6e", 918e12),
    ("v6 lite", 918e12),
    ("v6", 918e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 61.25e12),
    ("v2", 22.5e12),
]


def peak_flops_per_device(device_kind: str, backend: str) -> float:
    kind = device_kind.lower()
    if backend == "cpu":
        return 1e12  # nominal; CPU MFU is not meaningful
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return 197e12  # unknown TPU: conservative default


def _bench_one(ex, batch, cfg, iters):
    """Measure steady-state step time of a compiled executor.

    jax.block_until_ready does not reliably block through the axon
    tunnel, so every flush is a scalar readback (float(loss)); steady
    state is a long chained run after two warmup+flush rounds.
    """
    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(batch, cfg.seq_length, cfg.hidden_size), cfg.dtype.jnp)
    y = jnp.asarray(rs.randn(batch, cfg.seq_length, cfg.hidden_size), cfg.dtype.jnp)
    rng = jax.random.key(0)
    mets = ex.train_batch([x], y, rng)  # trace + compile + first run
    float(mets["loss"])
    for _ in range(3):  # absorb lazy recompilation
        mets = ex.train_batch([x], y, rng)
    float(mets["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        mets = ex.train_batch([x], y, rng)
    float(mets["loss"])  # single device->host readback flushes the chain
    dt = time.perf_counter() - t0
    return dt / iters


def child_main():
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the hosted-TPU sitecustomize force-selects its platform via
        # jax.config.update, overriding the env var — override it back
        # before any backend initializes (same trick as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")

    from flexflow_tpu import DataType, FFConfig, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerConfig, build_transformer

    backend = jax.default_backend()
    devs = jax.devices()
    n_dev = len(devs)
    kind = getattr(devs[0], "device_kind", backend)
    peak = peak_flops_per_device(kind, backend) * n_dev

    # BERT-Base-shaped encoder, bf16 activations (flash attention on TPU)
    cfg = TransformerConfig(
        num_layers=12,
        hidden_size=768,
        num_heads=12,
        ff_size=3072,
        seq_length=128,
        dtype=DataType.BFLOAT16,
    )
    batch = 16 * n_dev
    iters = 40 if backend != "cpu" else 3
    if backend == "cpu":  # keep the fallback path fast enough to finish
        cfg = TransformerConfig(
            num_layers=4, hidden_size=256, num_heads=4, ff_size=1024,
            seq_length=128, dtype=DataType.BFLOAT16,
        )
        batch = 4 * n_dev

    def build(only_dp: bool, budget: int):
        config = FFConfig(
            batch_size=batch,
            workers_per_node=n_dev,
            num_nodes=1,
            only_data_parallel=only_dp,
            search_budget=budget,
        )
        model = build_transformer(config, cfg)
        model.compile(optimizer=SGDOptimizer(lr=0.01), loss_type=LossType.MEAN_SQUARED_ERROR)
        return model

    model_dp = build(only_dp=True, budget=0)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(model_dp.executor.params))
    flops_per_token = 6.0 * n_params
    step_dp = _bench_one(model_dp.executor, batch, cfg, iters)

    # simulator validation (VERDICT r1 weakness 4): predicted vs measured
    sim_dp_ratio = None
    try:
        from flexflow_tpu.search.unity import predict_step_time

        pred_dp = predict_step_time(model_dp.graph, model_dp.config)
        sim_dp_ratio = round(pred_dp / step_dp, 3)
    except Exception as e:
        print(f"simulator prediction failed: {e!r}", file=sys.stderr)
        pred_dp = None

    t_search = time.perf_counter()
    step_s = sim_s_ratio = None
    try:
        model_s = build(only_dp=False, budget=5)
        search_s = time.perf_counter() - t_search
        step_s = _bench_one(model_s.executor, batch, cfg, iters)
        sr = getattr(model_s, "_search_result", None)
        if sr is not None and sr.best_cost > 0:
            sim_s_ratio = round(sr.best_cost / step_s, 3)
    except Exception as e:  # searched path must never kill the bench
        search_s = time.perf_counter() - t_search
        print(f"searched-strategy bench failed: {e!r}", file=sys.stderr)

    def mfu(step):
        if step is None:
            return None
        toks = batch * cfg.seq_length / step
        return round(toks * flops_per_token / peak, 4)

    # headline value and MFU describe the SAME configuration: the
    # searched strategy when it benched, else data-parallel
    headline_step = step_s if step_s is not None else step_dp
    samples_per_s = batch / headline_step
    dp_mfu, searched_mfu = mfu(step_dp), mfu(step_s)
    headline = mfu(headline_step)
    result = {
        "metric": "bert_base_seq128_train_throughput",
        "value": round(samples_per_s, 2),
        "unit": "samples/s",
        "vs_baseline": round(headline / 0.45, 4),
        "extra": {
            "backend": backend,
            "device_kind": kind,
            "devices": n_dev,
            "batch": batch,
            "params": n_params,
            "peak_flops": peak,
            "dp_step_ms": round(step_dp * 1e3, 2),
            "searched_step_ms": round(step_s * 1e3, 2) if step_s is not None else None,
            "dp_mfu": dp_mfu,
            "searched_mfu": searched_mfu,
            "mfu": headline,
            "search_s": round(search_s, 1),
            "sim_pred_over_measured_dp": sim_dp_ratio,
            "sim_pred_over_measured_searched": sim_s_ratio,
        },
    }
    print(json.dumps(result))


def _run_child(args, extra_env=None, timeout=None):
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env.update(extra_env or {})
    try:
        proc = subprocess.run(
            [sys.executable] + args,
            env=env,
            capture_output=True,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None, f"timed out after {timeout}s"
    sys.stderr.write(proc.stderr[-4000:])
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
            if isinstance(obj, dict) and "metric" in obj:
                return obj, None
        except json.JSONDecodeError:
            continue
    tail = (proc.stderr or proc.stdout or "")[-800:]
    return None, f"rc={proc.returncode}: {tail}"


_PROBE = (
    "import jax, json; d = jax.devices(); "
    "print(json.dumps({'metric': 'probe', 'backend': jax.default_backend(), 'n': len(d)}))"
)


def main():
    me = os.path.abspath(__file__)
    errors = []
    tpu_ok = False
    # Backend init over the tunnel can hang, not just error (round-1 it
    # errored; this session it hangs) — probe it in a killable child first.
    for delay in (0, 5, 15, 30):
        if delay:
            time.sleep(delay)
        obj, err = _run_child(["-c", _PROBE], timeout=90)
        if obj is not None:
            tpu_ok = obj.get("backend") != "cpu"
            break
        errors.append(f"probe: {err}")
    if tpu_ok:
        obj, err = _run_child([me], timeout=1800)
        if obj is not None:
            print(json.dumps(obj))
            return
        errors.append(f"bench: {err}")
    # TPU never came up (or bench died on it): CPU fallback so the
    # driver still gets a parseable number
    obj, err = _run_child([me], {"JAX_PLATFORMS": "cpu"}, timeout=1800)
    if obj is not None:
        if errors:
            obj.setdefault("extra", {})["fallback"] = "cpu_after_tpu_failure"
            obj["extra"]["tpu_errors"] = [e[-200:] for e in errors]
        print(json.dumps(obj))
        return
    errors.append(f"cpu: {err}")
    print(json.dumps({
        "metric": "bert_base_seq128_train_throughput",
        "value": 0.0,
        "unit": "samples/s",
        "vs_baseline": 0.0,
        "extra": {"error": (errors[-1] or "unknown")[-500:], "attempts": len(errors)},
    }))


if __name__ == "__main__":
    if os.environ.get(_CHILD_ENV) == "1":
        child_main()
    else:
        main()
