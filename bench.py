"""Benchmark driver: transformer training throughput, searched strategy
vs data-parallel vs tensor-parallel.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
The reference's headline is searched-strategy vs data-parallel on identical
hardware (scripts/osdi22ae/bert.sh); we report MFU for each strategy plus
simulator-validation ratios (predicted/measured) and the rank agreement
between simulated and measured strategy ordering.

TPU acquisition is a CAMPAIGN, not a retry (round-2 failure mode: 4x90s
probes gave up after ~7 min while the backend hung): explicit
JAX_PLATFORMS=tpu probes with exponential backoff under a total budget of
FF_BENCH_TPU_BUDGET_S (default 780s), each attempt's stderr recorded.
On first TPU contact the calibration suite runs and the measured op-cost
table is written both to the user cache and to the committed factory dir
(flexflow_tpu/search/calibration_data/) — reference analog: measured op
costs feeding the search, src/runtime/simulator.cc:588-628.

If the TPU never comes up the bench falls back to CPU on an 8-virtual-
device mesh (xla_force_host_platform_device_count) so dp-vs-searched
still exercises distinct strategies, the model is shrunk, and the metric
is renamed accordingly (a 4-layer/256-hidden model must not report a
bert_base metric).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

_CHILD_ENV = "_FF_BENCH_CHILD"

# (device_kind substring, peak bf16 FLOP/s per jax device), most specific first.
# v2/v3 expose one core per jax device; v4+ one (mega)chip per device.
_PEAK_BF16 = [
    ("v6e", 918e12),
    ("v6 lite", 918e12),
    ("v6", 918e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 61.25e12),
    ("v2", 22.5e12),
]


def train_flops_per_token(n_params: int, num_layers: int, seq_length: int, hidden_size: int) -> float:
    """6N (fwd+bwd matmul FLOPs per token) + attention score/value
    matmuls 12*L*S*H — the PaLM-appendix-style accounting; 6N alone
    undercounts the work. Shared with tools/tpu_evidence.py so the two
    evidence surfaces can't drift."""
    return 6.0 * n_params + 12.0 * num_layers * seq_length * hidden_size


def peak_flops_per_device(device_kind: str, backend: str) -> float:
    kind = device_kind.lower()
    if backend == "cpu":
        return 1e12  # nominal; CPU MFU is not meaningful
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return 197e12  # unknown TPU: conservative default


def _bench_one(ex, batch, cfg, iters):
    """Measure steady-state step time of a compiled executor.

    The timed unit is a traced multi-step window (train_batch_repeated:
    lax.scan over the train step inside ONE XLA program — the analog of
    the reference's Legion iteration tracing), so per-step host dispatch
    (several ms through the axon tunnel) is excluded from the step time,
    exactly as it is in a real fit loop that runs traced.
    jax.block_until_ready does not reliably block through the tunnel, so
    every flush is a scalar readback (float(loss)).
    """
    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(batch, cfg.seq_length, cfg.hidden_size), cfg.dtype.jnp)
    y = jnp.asarray(rs.randn(batch, cfg.seq_length, cfg.hidden_size), cfg.dtype.jnp)
    rng = jax.random.key(0)
    # warmup = compile + first run of the SAME traced-window program the
    # timed loop uses (a train_batch warmup would compile the single-step
    # program too — an unused, expensive extra XLA compile)
    mets = ex.train_batch_repeated([x], y, rng, num_steps=iters)
    float(mets["loss"])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        mets = ex.train_batch_repeated([x], y, rng, num_steps=iters)
        float(mets["loss"])  # device->host readback flushes the window
        best = min(best, time.perf_counter() - t0)
    return best / iters


def _capture_calibration(backend: str, kind: str):
    """On TPU contact, run the calibration suite and persist the measured
    table into the committed factory dir so every later search on this
    chip kind is calibrated (VERDICT r2 missing #1). Returns the repo
    path or None."""
    if backend == "cpu":
        return None
    try:
        from flexflow_tpu.search.calibration import _slug, load_or_calibrate

        cal = load_or_calibrate(allow_measure=True, device_kind=kind)
        if not cal.entries:
            return None
        repo_dir = Path(__file__).parent / "flexflow_tpu" / "search" / "calibration_data"
        path = repo_dir / f"opcosts_{_slug(kind)}.json"
        cal.save(path)
        print(f"calibration table written: {path}", file=sys.stderr)
        return str(path)
    except Exception as e:
        print(f"calibration capture failed: {e!r}", file=sys.stderr)
        return None


def child_main():
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the hosted-TPU sitecustomize force-selects its platform via
        # jax.config.update, overriding the env var — override it back
        # before any backend initializes (same trick as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    elif os.environ.get(_FORCE_PLATFORM_ENV) is not None:
        # mirror whatever platform forcing won the probe campaign
        jax.config.update("jax_platforms", os.environ[_FORCE_PLATFORM_ENV] or None)

    from flexflow_tpu import DataType, FFConfig, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerConfig, build_transformer

    backend = jax.default_backend()
    devs = jax.devices()
    n_dev = len(devs)
    kind = getattr(devs[0], "device_kind", backend)
    peak = peak_flops_per_device(kind, backend) * n_dev

    calibration_path = _capture_calibration(backend, kind)

    # BERT-Base-shaped encoder, bf16 activations (flash attention on TPU)
    cfg = TransformerConfig(
        num_layers=12,
        hidden_size=768,
        num_heads=12,
        ff_size=3072,
        seq_length=128,
        dtype=DataType.BFLOAT16,
    )
    batch = 32 * n_dev
    iters = 40 if backend != "cpu" else 3
    metric = "bert_base_seq128_train_throughput"
    if backend == "cpu":  # keep the fallback path fast enough to finish;
        # the metric name must describe the model actually run (ADVICE r2)
        cfg = TransformerConfig(
            num_layers=4, hidden_size=256, num_heads=4, ff_size=1024,
            seq_length=128, dtype=DataType.BFLOAT16,
        )
        batch = 4 * n_dev
        metric = "tiny_transformer_4l_h256_seq128_train_throughput"

    def build(only_dp: bool, budget: int, strategy_fn=None):
        config = FFConfig(
            batch_size=batch,
            workers_per_node=n_dev,
            num_nodes=1,
            only_data_parallel=only_dp,
            search_budget=budget,
        )
        model = build_transformer(config, cfg)
        # strategies must be built from THIS model's graph: guids are
        # process-unique per build, and a foreign strategy's shardings
        # would silently never apply (the model now rejects that)
        model.compile(
            optimizer=SGDOptimizer(lr=0.01),
            loss_type=LossType.MEAN_SQUARED_ERROR,
            strategy=strategy_fn(model.graph) if strategy_fn else None,
        )
        return model

    model_dp = build(only_dp=True, budget=0)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(model_dp.executor.params))
    flops_per_token = train_flops_per_token(
        n_params, cfg.num_layers, cfg.seq_length, cfg.hidden_size
    )
    step_dp = _bench_one(model_dp.executor, batch, cfg, iters)
    graph = model_dp.graph
    del model_dp

    # ---- honest simulator validation (VERDICT r2 weak #2): on CPU the
    # chip spec must be a CPU spec calibrated against measurement, never a
    # v5p roofline compared to a CPU wall clock
    from flexflow_tpu.parallel.machine import MachineSpec
    from flexflow_tpu.search.calibration import chip_spec_for, load_or_calibrate

    chip = chip_spec_for(kind) if backend != "cpu" else chip_spec_for("cpu")
    # calibrate against the UNSCALED chip (the suite runs on one device
    # with the whole machine behind it); the derates must not bake in the
    # virtual-device scaling below or the two factors cancel
    cal_machine = MachineSpec(num_nodes=1, devices_per_node=n_dev, chip=chip)
    calibration = load_or_calibrate(cal_machine, allow_measure=True, device_kind=kind)
    contention = None
    if backend == "cpu" and n_dev > 1:
        # N virtual CPU devices share ONE physical machine (thread pool):
        # per-device peak is 1/N of what the single-device calibration
        # suite measures, times CPU_FITTED_CONTENTION — fitted jointly
        # with the cpu preset's collective constants against quiet
        # dp/tp/hybrid measurements and LABELED as fitted-to-host-class
        # in the emitted JSON (it will not transfer exactly across very
        # different core counts)
        import dataclasses as _dc

        from flexflow_tpu.search.calibration import CPU_FITTED_CONTENTION

        contention = CPU_FITTED_CONTENTION
        chip = _dc.replace(
            chip,
            bf16_flops=chip.bf16_flops / (n_dev * contention),
            f32_flops=chip.f32_flops / (n_dev * contention),
            hbm_bandwidth=chip.hbm_bandwidth / (n_dev * contention),
        )
    machine = MachineSpec(num_nodes=1, devices_per_node=n_dev, chip=chip)

    sim_dp_ratio = None
    pred = {}
    try:
        from flexflow_tpu.parallel.strategy import (
            context_parallel_strategy,
            data_parallel_strategy,
            megatron_strategy,
            pipeline_strategy,
        )
        from flexflow_tpu.search.simulator import predict_strategy_time
        from flexflow_tpu.search.unity import predict_cp_time, predict_pipeline_time

        # FACTORIES, not instances: each measured model rebuilds the
        # strategy from its OWN graph (guids are process-unique per
        # build; a foreign strategy's shardings silently never applied
        # before the model grew a guard against it)
        factories = {"dp": lambda g: data_parallel_strategy(g, n_dev)}
        # tp and hybrid candidates (skip shapes that don't divide)
        if n_dev >= 2 and cfg.num_heads % 2 == 0:
            factories["tp"] = lambda g: megatron_strategy(g, dp=1, tp=min(n_dev, cfg.num_heads))
            if n_dev >= 4:
                factories["hybrid"] = lambda g: megatron_strategy(g, dp=n_dev // 2, tp=2)
        # pipeline candidate: a strategy family whose constants were NOT
        # fitted (fit set = dp/tp/hybrid), so its predicted/measured
        # ratio is a TRANSFER check of the cost model (VERDICT r4 weak
        # #3: in-band ratios on the fitting set alone are circular)
        pp_layout = None
        if n_dev >= 4 and cfg.num_layers % 2 == 0:
            factories["pp"] = lambda g: pipeline_strategy(g, pp=2, dp=n_dev // 2)
            pp_layout = (2, 1, 1)
        # cp: the second held-out family (ring-attention comm model)
        cp_layout = None
        if n_dev >= 4 and cfg.seq_length % 2 == 0:
            factories["cp"] = lambda g: context_parallel_strategy(
                g, dp=n_dev // 2, cp=2
            )
            cp_layout = (2, 1)
        for name, fn in factories.items():
            try:  # one failing candidate must not discard the others
                if name == "pp":
                    p = predict_pipeline_time(
                        graph, n_dev, batch, *pp_layout,
                        machine=machine, calibration=calibration,
                    )
                elif name == "cp":
                    p = predict_cp_time(
                        graph, n_dev, batch, *cp_layout,
                        machine=machine, calibration=calibration,
                    )
                else:
                    p = predict_strategy_time(
                        graph, fn(graph), machine, calibration=calibration
                    )
                if p is not None:
                    pred[name] = p
            except Exception as e:
                print(f"{name} prediction failed: {e!r}", file=sys.stderr)
    except Exception as e:
        print(f"simulator prediction failed: {e!r}", file=sys.stderr)
    sim_dp_ratio = round(pred["dp"] / step_dp, 3) if pred.get("dp") else None

    # ---- measure tp / hybrid / pp / cp so simulated vs measured rank
    # order is a reported fact, not an assumption (VERDICT r2 #2)
    measured = {"dp": step_dp}
    for name in ("tp", "hybrid", "pp", "cp"):
        if name not in pred:
            continue
        try:
            m = build(only_dp=True, budget=0, strategy_fn=factories[name])
            measured[name] = _bench_one(m.executor, batch, cfg, iters)
            del m
        except Exception as e:
            print(f"{name} strategy bench failed: {e!r}", file=sys.stderr)
    rank_agreement = best_agreement = fitted_rank_agreement = None
    sim_ratios = {}
    if len(measured) >= 2 and all(n in pred for n in measured):
        sim_rank = sorted(measured, key=lambda n: pred[n])
        meas_rank = sorted(measured, key=lambda n: measured[n])
        rank_agreement = sim_rank == meas_rank
        best_agreement = sim_rank[0] == meas_rank[0]
        sim_ratios = {n: round(pred[n] / measured[n], 3) for n in measured}
        # the regression guard ranks the FITTED families only; the full
        # rank over the held-out pp/cp transfer families can break on
        # near-ties (the per-strategy step_ms fields show the margins)
        fitted = [n for n in measured if n in ("dp", "tp", "hybrid")]
        if len(fitted) >= 2:  # one family alone ranks vacuously
            fitted_rank_agreement = sorted(fitted, key=lambda n: pred[n]) == sorted(
                fitted, key=lambda n: measured[n]
            )

    t_search = time.perf_counter()
    step_s = sim_s_ratio = None
    try:
        model_s = build(only_dp=False, budget=5)
        search_s = time.perf_counter() - t_search
        step_s = _bench_one(model_s.executor, batch, cfg, iters)
        # predict the searched strategy with the SAME machine/calibration
        # as the other ratios (the search's internal best_cost is costed
        # against the TPU chip it optimizes for, which is no signal when
        # the bench ran on a different backend)
        try:
            pred_s = predict_strategy_time(
                model_s.graph, model_s.strategy, machine, calibration=calibration
            )
            sim_s_ratio = round(pred_s / step_s, 3)
        except Exception as e:
            print(f"searched-strategy prediction failed: {e!r}", file=sys.stderr)
    except Exception as e:  # searched path must never kill the bench
        search_s = time.perf_counter() - t_search
        print(f"searched-strategy bench failed: {e!r}", file=sys.stderr)

    # ---- secondary: BERT-Large (the BASELINE.json north-star config,
    # scripts/osdi22ae/bert.sh) measured dp on this chip, same traced
    # window; never allowed to kill the primary result
    large = {}
    if backend != "cpu":
        try:
            lcfg = TransformerConfig(
                num_layers=24, hidden_size=1024, num_heads=16, ff_size=4096,
                seq_length=128, dtype=DataType.BFLOAT16,
            )
            lbatch = 16 * n_dev
            lconfig = FFConfig(
                batch_size=lbatch, workers_per_node=n_dev, num_nodes=1,
                only_data_parallel=True, search_budget=0,
            )
            lmodel = build_transformer(lconfig, lcfg)
            lmodel.compile(
                optimizer=SGDOptimizer(lr=0.01),
                loss_type=LossType.MEAN_SQUARED_ERROR,
            )
            lparams = sum(
                int(np.prod(p.shape)) for p in jax.tree.leaves(lmodel.executor.params)
            )
            lstep = _bench_one(lmodel.executor, lbatch, lcfg, 12)
            ltok = lbatch * lcfg.seq_length / lstep
            lf = train_flops_per_token(lparams, lcfg.num_layers, lcfg.seq_length, lcfg.hidden_size)
            large = {
                "bert_large_step_ms": round(lstep * 1e3, 2),
                "bert_large_mfu": round(ltok * lf / peak, 4),
                "bert_large_params": lparams,
                "bert_large_batch": lbatch,
            }
            del lmodel
        except Exception as e:
            print(f"bert-large bench failed: {e!r}", file=sys.stderr)

    def mfu(step):
        if step is None:
            return None
        toks = batch * cfg.seq_length / step
        return round(toks * flops_per_token / peak, 4)

    # headline value and MFU describe the SAME configuration: the
    # searched strategy when it benched, else data-parallel
    headline_step = step_s if step_s is not None else step_dp
    samples_per_s = batch / headline_step
    dp_mfu, searched_mfu = mfu(step_dp), mfu(step_s)
    headline = mfu(headline_step)
    result = {
        "metric": metric,
        "value": round(samples_per_s, 2),
        "unit": "samples/s",
        "vs_baseline": round(headline / 0.45, 4),
        "extra": {
            "backend": backend,
            "device_kind": kind,
            "devices": n_dev,
            "batch": batch,
            "params": n_params,
            "peak_flops": peak,
            "dp_step_ms": round(step_dp * 1e3, 2),
            "searched_step_ms": round(step_s * 1e3, 2) if step_s is not None else None,
            "tp_step_ms": round(measured["tp"] * 1e3, 2) if "tp" in measured else None,
            "hybrid_step_ms": round(measured["hybrid"] * 1e3, 2) if "hybrid" in measured else None,
            "pp_step_ms": round(measured["pp"] * 1e3, 2) if "pp" in measured else None,
            "cp_step_ms": round(measured["cp"] * 1e3, 2) if "cp" in measured else None,
            # round-5 honesty fixes make CPU values incomparable to r4:
            # (a) tp/hybrid strategies ACTUALLY apply now (they silently
            # ran replicated before), (b) bf16 models really run bf16
            # dense layers — emulated and slower on CPU, faster on TPU
            "cpu_value_not_comparable_to_r4": (
                "bf16 dense layers now really run in bf16 (CPU emulation "
                "is slower than the f32 they silently used before); "
                "tp/hybrid now measure the real strategies"
            ) if backend == "cpu" else None,
            "dp_mfu": dp_mfu,
            "searched_mfu": searched_mfu,
            "mfu": headline,
            "search_s": round(search_s, 1),
            "sim_pred_over_measured_dp": sim_dp_ratio,
            "sim_pred_over_measured_searched": sim_s_ratio,
            "sim_pred_over_measured": sim_ratios or None,
            "sim_rank_agreement": rank_agreement,
            "sim_rank_agreement_fitted": fitted_rank_agreement,
            "sim_best_strategy_agreement": best_agreement,
            "calibration_table": calibration_path,
            "calibration_kind": calibration.device_kind,
            # CPU fallback only: the virtual-mesh compute scaling factor,
            # fitted to the class of host the constants were tuned on
            "cpu_contention_fitted_to_host_class": contention,
            **large,
        },
    }
    print(json.dumps(result))


def _emit_result(obj, ok: bool = True):
    """Emit the final bench result durably (VERDICT r3 weak #4 / ask #7):
    stdout carries EXACTLY one JSON line (diagnostics all go to stderr,
    flushed first so a merged stream can't interleave after the JSON),
    and the same object is written to BENCH_RESULT.json so a dead tunnel
    or a driver parse quirk never erases a round's evidence. A FAILED run
    (ok=False) writes BENCH_FAILED.json instead — overwriting the last
    good result with a zero-value failure record would erase exactly the
    evidence this helper exists to preserve."""
    name = "BENCH_RESULT.json" if ok else "BENCH_FAILED.json"
    try:
        path = Path(__file__).parent / name
        if ok and obj.get("extra", {}).get("backend") == "cpu" and path.exists():
            try:
                prev = json.loads(path.read_text())
                if prev.get("extra", {}).get("backend") == "tpu":
                    # a CPU fallback must not clobber real on-chip
                    # evidence from an earlier run
                    name = "BENCH_RESULT_CPU.json"
                    path = Path(__file__).parent / name
            except (json.JSONDecodeError, OSError):
                pass
        path.write_text(json.dumps(obj, indent=1) + "\n")
        wrote_durable = True
    except OSError as e:
        wrote_durable = False
        print(f"could not write {name}: {e!r}", file=sys.stderr)
    sys.stderr.flush()
    # stdout must stay small enough for the driver's tail window (r4's
    # BENCH_r04.json came back parsed:null because six ~400-char
    # tpu_errors entries overflowed it). Full detail lives in the durable
    # file written above; stdout gets a count + one capped error — but
    # only when that file actually landed, else stdout keeps everything
    # (the errors would otherwise exist nowhere).
    out = obj
    errs = obj.get("extra", {}).get("tpu_errors")
    if errs and wrote_durable:
        out = dict(obj)
        out["extra"] = {
            k: v for k, v in obj["extra"].items() if k != "tpu_errors"
        }
        out["extra"]["tpu_probe_failures"] = len(errs)
        out["extra"]["last_error"] = str(errs[-1])[-200:]
        out["extra"]["error_detail_in"] = name
    print(json.dumps(out), flush=True)


def _run_child(args, extra_env=None, timeout=None):
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    env.update(extra_env or {})
    try:
        proc = subprocess.run(
            [sys.executable] + args,
            env=env,
            capture_output=True,
            text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        tail = ""
        for label, s in (("stderr", e.stderr), ("stdout", e.stdout)):
            if s:
                text = s.decode(errors="replace") if isinstance(s, bytes) else s
                tail = f"; {label}: {text[-300:]}"
                break
        return None, f"timed out after {timeout:.0f}s{tail}"
    sys.stderr.write(proc.stderr[-4000:])
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
            if isinstance(obj, dict) and "metric" in obj:
                return obj, None
        except json.JSONDecodeError:
            continue
    tail = (proc.stderr or proc.stdout or "")[-800:]
    return None, f"rc={proc.returncode}: {tail}"


# the probe runs a real (tiny) matmul so a backend that initializes but
# hangs at dispatch is caught at probe time, not mid-bench
_PROBE = (
    "import os, json; import jax; "
    "fp = os.environ.get('FF_BENCH_FORCE_PLATFORM'); "
    "fp is not None and jax.config.update('jax_platforms', fp or None); "
    "d = jax.devices(); "
    "import jax.numpy as jnp; x = jnp.ones((256, 256), jnp.bfloat16); "
    "v = float((x @ x).sum()); "
    "print(json.dumps({'metric': 'probe', 'backend': jax.default_backend(), "
    "'n': len(d), 'kind': getattr(d[0], 'device_kind', ''), 'sum': v}))"
)

# Platform configs to probe, in order. {} inherits the ambient
# JAX_PLATFORMS (tunneled TPUs may register under a bridge platform name
# — e.g. the axon tunnel sets JAX_PLATFORMS=axon yet reports backend
# 'tpu' — so forcing JAX_PLATFORMS=tpu there fails with 'no TPU found'
# while the inherited config works). Explicit 'tpu' and autodetect are
# the fallbacks for plainly-attached chips; those also set
# _FORCE_PLATFORM_ENV, which the probe/child apply via
# jax.config.update — hosted sitecustomizes force-select a platform
# through jax.config, overriding the env var alone.
_FORCE_PLATFORM_ENV = "FF_BENCH_FORCE_PLATFORM"
_PLATFORM_CONFIGS = [
    {},
    {"JAX_PLATFORMS": "tpu", _FORCE_PLATFORM_ENV: "tpu"},
    {"JAX_PLATFORMS": "", _FORCE_PLATFORM_ENV: ""},
]


def main():
    me = os.path.abspath(__file__)
    errors = []
    tpu_ok = False
    # TPU acquisition campaign (VERDICT r2 next-round #1): rotate through
    # _PLATFORM_CONFIGS (inherit first — tunneled chips register under
    # bridge platform names), total budget ~13 min, exponential backoff,
    # per-attempt timeout 150s, full stderr capture per attempt.
    budget = float(os.environ.get("FF_BENCH_TPU_BUDGET_S", "780"))
    start = time.monotonic()
    delays = [0, 10, 20, 40, 60, 90]
    attempt = 0
    tpu_env = None
    while True:
        elapsed = time.monotonic() - start
        if elapsed >= budget:
            errors.append(f"budget exhausted after {elapsed:.0f}s / {attempt} probes")
            break
        delay = delays[min(attempt, len(delays) - 1)]
        if delay:
            time.sleep(min(delay, max(0.0, budget - (time.monotonic() - start))))
        cfg_env = _PLATFORM_CONFIGS[attempt % len(_PLATFORM_CONFIGS)]
        per_try = min(150.0, max(30.0, budget - (time.monotonic() - start)))
        obj, err = _run_child(["-c", _PROBE], cfg_env, timeout=per_try)
        # only 'tpu' counts: the inherit/autodetect configs could surface
        # some other accelerator, which must not masquerade as the TPU path
        if obj is not None and obj.get("backend") == "tpu":
            tpu_ok = True
            tpu_env = cfg_env
            break
        errors.append(f"probe[{attempt}] {cfg_env or 'inherit'} t+{elapsed:.0f}s: {err or 'backend=cpu'}")
        attempt += 1
    if tpu_ok:
        obj, err = _run_child([me], tpu_env, timeout=2400)
        if obj is not None:
            _emit_result(obj)
            return
        errors.append(f"bench: {err}")
    # TPU never came up (or bench died on it): CPU fallback on an
    # 8-virtual-device mesh so dp-vs-searched still compares distinct
    # strategies (ADVICE r2: a devices=1 comparison carries no signal)
    xla_flags = os.environ.get("XLA_FLAGS", "")
    cpu_env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (xla_flags + " --xla_force_host_platform_device_count=8").strip(),
    }
    obj, err = _run_child([me], cpu_env, timeout=2400)
    if obj is not None:
        if errors:
            obj.setdefault("extra", {})["fallback"] = "cpu_after_tpu_failure"
            obj["extra"]["tpu_errors"] = [e[-400:] for e in errors]
        _emit_result(obj)
        return
    errors.append(f"cpu: {err}")
    _emit_result({
        "metric": "train_throughput_bench_failed",
        "value": 0.0,
        "unit": "samples/s",
        "vs_baseline": 0.0,
        "extra": {"error": (errors[-1] or "unknown")[-500:], "attempts": len(errors)},
    }, ok=False)


if __name__ == "__main__":
    if os.environ.get(_CHILD_ENV) == "1":
        child_main()
    else:
        main()
