"""Benchmark driver: BERT training throughput on the available TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no absolute numbers (BASELINE.md) — its story is
searched-strategy vs data-parallel on identical hardware. Single-chip,
we report training throughput and MFU; vs_baseline is MFU relative to
the 45%-MFU north star from BASELINE.json.

Measurement notes for the tunneled chip ("axon"): jax.block_until_ready
does not reliably block through the tunnel, so every flush is a scalar
readback (float(loss)), and steady state is measured over a long chained
run after two warmup+flush rounds (the first absorbs trace+XLA compile,
the second any lazy backend recompilation).
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from flexflow_tpu import DataType, FFConfig, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerConfig, build_transformer

    backend = jax.default_backend()
    n_dev = len(jax.devices())
    # BERT-Base-shaped encoder, bf16 activations
    cfg = TransformerConfig(
        num_layers=12,
        hidden_size=768,
        num_heads=12,
        ff_size=3072,
        seq_length=128,
        dtype=DataType.BFLOAT16,
    )
    batch = 16 * n_dev
    config = FFConfig(batch_size=batch)
    model = build_transformer(config, cfg)
    model.compile(optimizer=SGDOptimizer(lr=0.01), loss_type=LossType.MEAN_SQUARED_ERROR)
    ex = model.executor

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(batch, cfg.seq_length, cfg.hidden_size), cfg.dtype.jnp)
    y = jnp.asarray(rs.randn(batch, cfg.seq_length, cfg.hidden_size), cfg.dtype.jnp)
    rng = jax.random.key(0)

    # warmup round 1: trace + compile + first execution
    mets = ex.train_batch([x], y, rng)
    float(mets["loss"])
    # warmup round 2: absorb any lazily-triggered recompilation
    for _ in range(3):
        mets = ex.train_batch([x], y, rng)
    float(mets["loss"])

    iters = 40 if backend != "cpu" else 5
    t0 = time.perf_counter()
    for _ in range(iters):
        mets = ex.train_batch([x], y, rng)
    float(mets["loss"])  # single device->host readback flushes the chain
    dt = time.perf_counter() - t0
    step_ms = dt * 1e3 / iters

    samples_per_s = iters * batch / dt
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(ex.params))
    tokens_per_s = samples_per_s * cfg.seq_length
    train_flops_per_token = 6.0 * n_params
    achieved_flops = tokens_per_s * train_flops_per_token
    peak = 197e12 * n_dev if backend != "cpu" else 1e12  # v5e bf16 peak per chip
    mfu = achieved_flops / peak
    result = {
        "metric": "bert_base_seq128_train_throughput",
        "value": round(samples_per_s, 2),
        "unit": "samples/s",
        "vs_baseline": round(mfu / 0.45, 4),
        "extra": {
            "backend": backend,
            "devices": n_dev,
            "batch": batch,
            "params": n_params,
            "step_ms": round(step_ms, 2),
            "mfu": round(mfu, 4),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
