// Allreduce schedule engine (fork parity): expand an allreduce over n
// participants into synchronized p2p rounds for ring / butterfly /
// double-binary-tree patterns, and simulate them over a machine model
// with per-round link congestion.
//
// Reference: AllreduceHelper (simulator.h:614-651), pattern generators
// (simulator.cc:2870+), simulation_with_allreduce_optimize
// (simulator.cc:1721). Python mirror: search/simulator.py
// AllreduceHelper / LogicalTaskgraphSimulator.simulate_allreduce —
// generation order and congestion accounting match it transfer-for-
// transfer so both backends agree.

#include <algorithm>
#include <cmath>
#include <map>

#include "ffcore.h"
#include "ffcore_internal.h"

namespace ffcore {

namespace {

struct Transfer {
  int32_t src, dst;
  double bytes;
};
using Rounds = std::vector<std::vector<Transfer>>;

Rounds ring_rounds(const int32_t *p, int32_t n, double nbytes) {
  Rounds rounds;
  if (n <= 1) return rounds;
  double chunk = nbytes / n;
  for (int32_t r = 0; r < 2 * (n - 1); r++) {  // reduce-scatter + all-gather
    std::vector<Transfer> round;
    for (int32_t i = 0; i < n; i++) round.push_back({p[i], p[(i + 1) % n], chunk});
    rounds.push_back(std::move(round));
  }
  return rounds;
}

Rounds butterfly_rounds(const int32_t *p, int32_t n, double nbytes) {
  Rounds rounds;
  if (n <= 1) return rounds;
  int32_t steps = std::max(1, (int32_t)std::ceil(std::log2((double)n)));
  double size = nbytes;
  for (int32_t k = 0; k < steps; k++) {  // recursive halving
    int32_t dist = 1 << k;
    std::vector<Transfer> round;
    for (int32_t i = 0; i < n; i++)
      if ((i ^ dist) < n) round.push_back({p[i], p[i ^ dist], size / 2});
    rounds.push_back(std::move(round));
    size /= 2;
  }
  for (int32_t k = steps - 1; k >= 0; k--) {  // recursive doubling
    int32_t dist = 1 << k;
    size *= 2;
    std::vector<Transfer> round;
    for (int32_t i = 0; i < n; i++)
      if ((i ^ dist) < n) round.push_back({p[i], p[i ^ dist], size / 2});
    rounds.push_back(std::move(round));
  }
  return rounds;
}

Rounds dbt_rounds(const int32_t *p, int32_t n, double nbytes) {
  Rounds rounds;
  if (n <= 1) return rounds;
  double half = nbytes / 2;  // each tree carries half the payload
  auto tree_rounds = [&](const std::vector<int32_t> &order) {
    int32_t depth = std::max(1, (int32_t)std::ceil(std::log2((double)n)));
    Rounds up;
    for (int32_t lvl = 0; lvl < depth; lvl++) {  // reduce toward the root
      int32_t step = 1 << (lvl + 1);
      std::vector<Transfer> r;
      for (int32_t i = 0; i < n; i += step) {
        int32_t j = i + (1 << lvl);
        if (j < n) r.push_back({order[j], order[i], half});
      }
      if (!r.empty()) up.push_back(std::move(r));
    }
    Rounds down;  // broadcast back down: reversed rounds, flipped edges
    for (auto it = up.rbegin(); it != up.rend(); ++it) {
      std::vector<Transfer> r;
      for (const auto &t : *it) r.push_back({t.dst, t.src, t.bytes});
      down.push_back(std::move(r));
    }
    Rounds all = up;
    all.insert(all.end(), down.begin(), down.end());
    return all;
  };
  std::vector<int32_t> fwd(p, p + n), rev(fwd.rbegin(), fwd.rend());
  Rounds t1 = tree_rounds(fwd), t2 = tree_rounds(rev);
  size_t len = std::max(t1.size(), t2.size());
  for (size_t i = 0; i < len; i++) {
    std::vector<Transfer> r;
    if (i < t1.size()) r.insert(r.end(), t1[i].begin(), t1[i].end());
    if (i < t2.size()) r.insert(r.end(), t2[i].begin(), t2[i].end());
    rounds.push_back(std::move(r));
  }
  return rounds;
}

}  // namespace

double allreduce_simulate(MachineModel &mm, const int32_t *participants,
                          int32_t n, double nbytes, int32_t pattern) {
  Rounds rounds;
  switch (pattern) {
    case 0: rounds = ring_rounds(participants, n, nbytes); break;
    case 1: rounds = butterfly_rounds(participants, n, nbytes); break;
    case 2: rounds = dbt_rounds(participants, n, nbytes); break;
    default: return -1.0;
  }
  bool networked = mm.kind == MachineModel::NETWORKED;
  double total = 0.0;
  for (const auto &round : rounds) {
    std::map<std::pair<int32_t, int32_t>, double> link_load;
    double round_t = 0.0;
    for (const auto &tr : round) {
      double t = mm.comm_time(tr.src, tr.dst, tr.bytes);
      if (networked) {
        int32_t sn = mm.node_of(tr.src), dn = mm.node_of(tr.dst);
        double cong = 1.0;
        if (sn != dn) {
          const auto &rs = mm.routes(sn, dn);
          if (!rs.empty()) {  // only the primary route congests (python parity)
            const auto &path = rs[0];
            for (size_t i = 0; i + 1 < path.size(); i++) {
              auto key = std::make_pair(path[i], path[i + 1]);
              link_load[key] += 1.0;
              cong = std::max(cong, link_load[key]);
            }
          }
        }
        t *= cong;
      }
      round_t = std::max(round_t, t);
    }
    total += round_t;
  }
  return total;
}

}  // namespace ffcore

extern "C" {

double ffc_allreduce_simulate(ffc_mm_t *mm, const int32_t *participants,
                              int32_t n, double nbytes, int32_t pattern) {
  return ffcore::allreduce_simulate(*mm, participants, n, nbytes, pattern);
}

int32_t ffc_allreduce_optimize(ffc_mm_t *mm, const int32_t *participants,
                               int32_t n, double nbytes, double *out_times) {
  int32_t best = 0;
  double best_t = std::numeric_limits<double>::infinity();
  for (int32_t pat = 0; pat < 3; pat++) {
    double t = ffcore::allreduce_simulate(*mm, participants, n, nbytes, pat);
    if (out_times) out_times[pat] = t;
    if (t < best_t) {
      best_t = t;
      best = pat;
    }
  }
  return best;
}

}  // extern "C"
