// Machine models: flat two-level (SimpleMachineModel parity) and the
// fork's topology-aware NetworkedMachineModel with routing strategies
// (reference: src/runtime/machine_model.cc, network.cc:48-640;
// python mirror: flexflow_tpu/search/machine_model.py).
//
// The Dijkstra here replicates the Python implementation's tie-breaking
// ((dist, node) lexicographic pops, strict improvement, neighbors in
// index order) so route choices — and therefore simulated times — are
// identical across backends.

#include <algorithm>
#include <cmath>
#include <queue>
#include <set>

#include "ffcore.h"
#include "ffcore_internal.h"

namespace ffcore {

namespace {

constexpr double kInf = std::numeric_limits<double>::infinity();

// weight_fn(u, v, links) -> edge weight; removed edges get +inf.
template <typename WeightFn>
std::vector<int32_t> dijkstra(const MachineModel &mm, int32_t src, int32_t dst,
                              WeightFn weight_fn) {
  const int32_t n = mm.num_endpoints();
  std::vector<double> dist(n, kInf);
  std::vector<int32_t> prev(n, -1);
  dist[src] = 0.0;
  using Item = std::pair<double, int32_t>;
  std::priority_queue<Item, std::vector<Item>, std::greater<Item>> pq;
  pq.push({0.0, src});
  while (!pq.empty()) {
    auto [d, u] = pq.top();
    pq.pop();
    if (u == dst) break;
    if (d > dist[u]) continue;
    for (int32_t v = 0; v < n; v++) {
      int32_t links = mm.links(u, v);
      if (!links) continue;
      double w = weight_fn(u, v, links);
      double nd = d + w;
      if (nd < dist[v]) {
        dist[v] = nd;
        prev[v] = u;
        pq.push({nd, v});
      }
    }
  }
  if (dist[dst] == kInf) return {};
  std::vector<int32_t> path = {dst};
  while (path.back() != src) {
    int32_t p = prev[path.back()];
    if (p < 0) return {};
    path.push_back(p);
  }
  std::reverse(path.begin(), path.end());
  return path;
}

std::vector<std::vector<int32_t>> compute_routes(MachineModel &mm, int32_t src,
                                                 int32_t dst) {
  std::vector<std::vector<int32_t>> paths;
  if (mm.routing == 0) {  // hop-count shortest
    auto p = dijkstra(mm, src, dst, [](int32_t, int32_t, int32_t) { return 1.0; });
    if (!p.empty()) paths.push_back(std::move(p));
  } else if (mm.routing == 1) {  // weighted by inverse multiplicity
    auto p = dijkstra(mm, src, dst,
                      [](int32_t, int32_t, int32_t l) { return 1.0 / l; });
    if (!p.empty()) paths.push_back(std::move(p));
  } else {  // ECMP: k paths by removing the first hop of the last path
    std::set<std::pair<int32_t, int32_t>> removed;
    auto w = [&removed](int32_t u, int32_t v, int32_t) {
      return removed.count({u, v}) ? kInf : 1.0;
    };
    auto base = dijkstra(mm, src, dst, w);
    if (base.empty()) return paths;
    size_t base_len = base.size();
    paths.push_back(std::move(base));
    while ((int32_t)paths.size() < mm.ecmp_max_paths) {
      const auto &last = paths.back();
      removed.insert({last[0], last[1]});
      auto p = dijkstra(mm, src, dst, w);
      if (p.empty() || p.size() > base_len) break;
      if (std::find(paths.begin(), paths.end(), p) == paths.end())
        paths.push_back(std::move(p));
      else
        break;  // same path re-found: no further diversity available
    }
  }
  return paths;
}

}  // namespace

const std::vector<std::vector<int32_t>> &MachineModel::routes(int32_t src_node,
                                                              int32_t dst_node) {
  auto key = std::make_pair(src_node, dst_node);
  auto it = route_cache.find(key);
  if (it == route_cache.end())
    it = route_cache.emplace(key, compute_routes(*this, src_node, dst_node))
             .first;
  return it->second;
}

double MachineModel::comm_time(int32_t src_dev, int32_t dst_dev,
                               double nbytes) {
  if (kind == SIMPLE) {
    if (src_dev == dst_dev) return 0.0;
    bool same_node = src_dev / devices_per_node == dst_dev / devices_per_node;
    if (same_node) return ici_latency + nbytes / ici_bandwidth;
    return dcn_latency + nbytes / dcn_bandwidth;
  }
  // networked
  int32_t sn = node_of(src_dev), dn = node_of(dst_dev);
  if (sn == dn) {
    if (src_dev == dst_dev) return 0.0;
    return ici_latency + nbytes / ici_bandwidth;
  }
  const auto &rs = routes(sn, dn);
  if (rs.empty()) return link_latency + nbytes / link_bandwidth;
  double share = nbytes / (double)rs.size();
  double t = 0.0;
  for (const auto &path : rs) {
    double bw = kInf;
    for (size_t i = 0; i + 1 < path.size(); i++) {
      int32_t l = links(path[i], path[i + 1]);
      bw = std::min(bw, link_bandwidth * std::max(1, l));
    }
    double lat = link_latency * (double)(path.size() - 1);
    t = std::max(t, lat + share / bw);
  }
  return t;
}

}  // namespace ffcore

extern "C" {

ffc_mm_t *ffc_mm_create_simple(int32_t num_nodes, int32_t devices_per_node,
                               double ici_latency, double ici_bandwidth,
                               double dcn_latency, double dcn_bandwidth) {
  auto *mm = new ffc_machine_model();
  mm->kind = ffcore::MachineModel::SIMPLE;
  mm->num_nodes = num_nodes;
  mm->devices_per_node = devices_per_node;
  mm->ici_latency = ici_latency;
  mm->ici_bandwidth = ici_bandwidth;
  mm->dcn_latency = dcn_latency;
  mm->dcn_bandwidth = dcn_bandwidth;
  return mm;
}

ffc_mm_t *ffc_mm_create_networked(int32_t num_nodes, int32_t num_switches,
                                  int32_t devices_per_node,
                                  const int32_t *conn, double link_latency,
                                  double link_bandwidth, double ici_latency,
                                  double ici_bandwidth, int32_t routing,
                                  int32_t ecmp_max_paths) {
  auto *mm = new ffc_machine_model();
  mm->kind = ffcore::MachineModel::NETWORKED;
  mm->num_nodes = num_nodes;
  mm->num_switches = num_switches;
  mm->devices_per_node = devices_per_node;
  int32_t e = num_nodes + num_switches;
  mm->conn.assign(conn, conn + (size_t)e * e);
  mm->link_latency = link_latency;
  mm->link_bandwidth = link_bandwidth;
  mm->ici_latency = ici_latency;
  mm->ici_bandwidth = ici_bandwidth;
  mm->routing = routing;
  mm->ecmp_max_paths = ecmp_max_paths > 0 ? ecmp_max_paths : 4;
  return mm;
}

void ffc_mm_destroy(ffc_mm_t *mm) { delete mm; }

int32_t ffc_mm_num_devices(const ffc_mm_t *mm) { return mm->num_devices(); }

double ffc_mm_comm_time(ffc_mm_t *mm, int32_t src_dev, int32_t dst_dev,
                        double nbytes) {
  return mm->comm_time(src_dev, dst_dev, nbytes);
}

int32_t ffc_mm_get_routes(ffc_mm_t *mm, int32_t src_node, int32_t dst_node,
                          int32_t *out, int32_t *path_lens, int32_t max_paths,
                          int32_t max_len) {
  if (mm->kind != ffcore::MachineModel::NETWORKED) return -1;
  if (src_node == dst_node) return 0;
  const auto &rs = mm->routes(src_node, dst_node);
  int32_t np = std::min((int32_t)rs.size(), max_paths);
  for (int32_t p = 0; p < np; p++) {
    int32_t len = std::min((int32_t)rs[p].size(), max_len);
    path_lens[p] = len;
    for (int32_t i = 0; i < len; i++) out[p * max_len + i] = rs[p][i];
  }
  return np;
}

}  // extern "C"
