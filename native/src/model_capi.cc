// Full-model C API: build, compile, and train a model from pure C
// (VERDICT r2 missing #4 / next-round #7).
//
// Reference analog: python/flexflow_c.cc (1937 LoC) wraps the C++
// FFModel so cffi/Python can drive it; there, C wraps C++ and Python
// sits on top. In this framework the compute path is JAX/XLA, so the
// layering INVERTS: the C API embeds a CPython interpreter (exactly as
// the reference's python/main.cc embeds CPython inside a Legion task)
// and drives flexflow_tpu through it. A non-Python host links
// libffcore.so + libpython and gets the whole framework — graph
// building, unity search, XLA compilation, training — behind a flat
// C ABI (tests/native/c_model_driver.c proves the loop end to end).
//
// Every entry point is GIL-correct: callable both from a pure-C host
// (which may never have initialized Python) and from inside a Python
// process that loaded libffcore via ctypes (ctypes drops the GIL around
// foreign calls; PyGILState_Ensure re-acquires it).
#include "../include/ffcore.h"

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Model {
  PyObject *model = nullptr;    // flexflow_tpu.model.FFModel
  PyObject *tensors = nullptr;  // list of Tensor handles (index = id)
  PyObject *rng = nullptr;      // jax PRNG key, set at compile
  bool compiled = false;
};

struct Gil {
  PyGILState_STATE st;
  Gil() : st(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st); }
};

bool ensure_python() {
  if (Py_IsInitialized()) return true;
  Py_InitializeEx(0);
  if (!Py_IsInitialized()) return false;
  // the embedding host (a plain C program) finds flexflow_tpu via
  // PYTHONPATH, matching how the reference's embedded interpreter found
  // the flexflow package.
  // Release the GIL the initializer left held by THIS thread, so a
  // different host thread's PyGILState_Ensure doesn't deadlock; every
  // entry point re-acquires via Gil{}.
  PyEval_SaveThread();
  return true;
}

PyObject *import_attr(const char *mod, const char *attr) {
  PyObject *m = PyImport_ImportModule(mod);
  if (!m) return nullptr;
  PyObject *a = PyObject_GetAttrString(m, attr);
  Py_DECREF(m);
  return a;
}

void report_and_clear() {
  if (PyErr_Occurred()) PyErr_Print();
}

int64_t push_tensor(Model *m, PyObject *t /* stolen */) {
  if (!t) return -1;
  PyList_Append(m->tensors, t);
  Py_DECREF(t);
  return PyList_Size(m->tensors) - 1;
}

PyObject *get_tensor(Model *m, int64_t id) {  // borrowed
  if (id < 0 || id >= PyList_Size(m->tensors)) return nullptr;
  return PyList_GetItem(m->tensors, id);
}

// host buffer (C double, row-major) -> jnp.float32/int32 array
PyObject *array_from(const double *data, const int64_t *shape, int32_t ndims,
                     bool as_int) {
  PyObject *np = PyImport_ImportModule("numpy");
  if (!np) return nullptr;
  int64_t n = 1;
  for (int32_t i = 0; i < ndims; ++i) n *= shape[i];
  PyObject *bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data), n * (int64_t)sizeof(double));
  PyObject *arr = PyObject_CallMethod(np, "frombuffer", "Os", bytes, "float64");
  Py_XDECREF(bytes);
  if (!arr) {
    Py_DECREF(np);
    return nullptr;
  }
  PyObject *shp = PyTuple_New(ndims);
  for (int32_t i = 0; i < ndims; ++i)
    PyTuple_SetItem(shp, i, PyLong_FromLongLong(shape[i]));
  PyObject *reshaped = PyObject_CallMethod(arr, "reshape", "O", shp);
  Py_DECREF(arr);
  Py_DECREF(shp);
  if (!reshaped) {
    Py_DECREF(np);
    return nullptr;
  }
  PyObject *cast =
      PyObject_CallMethod(reshaped, "astype", "s", as_int ? "int32" : "float32");
  Py_DECREF(reshaped);
  Py_DECREF(np);
  return cast;
}

// obj.meth(*args, name=name) — the builder methods take `name` as a
// keyword (positional slots hold dtype/axis/use_bias defaults)
PyObject *call_named(PyObject *obj, const char *meth, PyObject *args /*stolen*/,
                     const char *name) {
  PyObject *fn = PyObject_GetAttrString(obj, meth);
  if (!fn) {
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *kw = Py_BuildValue("{s:s}", "name", name ? name : "");
  PyObject *r = PyObject_Call(fn, args, kw);
  Py_DECREF(fn);
  Py_DECREF(kw);
  Py_DECREF(args);
  return r;
}

}  // namespace

extern "C" {

ffc_model_t *ffc_model_create(int32_t batch_size, int32_t workers_per_node,
                              int32_t num_nodes, int32_t search_budget) {
  if (!ensure_python()) return nullptr;
  Gil gil;
  PyObject *cfg_cls = import_attr("flexflow_tpu.config", "FFConfig");
  PyObject *model_cls = import_attr("flexflow_tpu.model", "FFModel");
  if (!cfg_cls || !model_cls) {
    report_and_clear();
    Py_XDECREF(cfg_cls);
    Py_XDECREF(model_cls);
    return nullptr;
  }
  PyObject *kwargs = Py_BuildValue(
      "{s:i,s:i,s:i,s:i}", "batch_size", batch_size, "workers_per_node",
      workers_per_node, "num_nodes", num_nodes, "search_budget", search_budget);
  PyObject *empty = PyTuple_New(0);
  PyObject *cfg = PyObject_Call(cfg_cls, empty, kwargs);
  Py_DECREF(kwargs);
  Py_DECREF(cfg_cls);
  PyObject *model =
      cfg ? PyObject_CallFunctionObjArgs(model_cls, cfg, nullptr) : nullptr;
  Py_XDECREF(cfg);
  Py_DECREF(model_cls);
  Py_DECREF(empty);
  if (!model) {
    report_and_clear();
    return nullptr;
  }
  Model *m = new Model();
  m->model = model;
  m->tensors = PyList_New(0);
  return reinterpret_cast<ffc_model_t *>(m);
}

ffc_model_t *ffc_model_create_json(const char *config_json) {
  // Full-config create: any FFConfig field by name. The dataclass is the
  // single schema; new flags (zero_optimizer, grad_accum_steps,
  // trace_window, pipeline_stages, ...) need no new C glue.
  if (!ensure_python()) return nullptr;
  Gil gil;
  PyObject *cfg_cls = import_attr("flexflow_tpu.config", "FFConfig");
  PyObject *model_cls = import_attr("flexflow_tpu.model", "FFModel");
  PyObject *jsonmod = PyImport_ImportModule("json");
  if (!cfg_cls || !model_cls || !jsonmod) {
    report_and_clear();
    Py_XDECREF(cfg_cls);
    Py_XDECREF(model_cls);
    Py_XDECREF(jsonmod);
    return nullptr;
  }
  PyObject *kwargs = PyObject_CallMethod(jsonmod, "loads", "s",
                                         config_json ? config_json : "{}");
  Py_DECREF(jsonmod);
  PyObject *model = nullptr;
  if (kwargs && PyDict_Check(kwargs)) {
    PyObject *empty = PyTuple_New(0);
    PyObject *cfg = PyObject_Call(cfg_cls, empty, kwargs);
    if (cfg) {
      model = PyObject_CallFunctionObjArgs(model_cls, cfg, nullptr);
    }
    Py_XDECREF(cfg);
    Py_DECREF(empty);
  } else if (kwargs) {
    PyErr_SetString(PyExc_TypeError, "config_json must be a JSON object");
  }
  Py_XDECREF(kwargs);
  Py_DECREF(cfg_cls);
  Py_DECREF(model_cls);
  if (!model) {
    report_and_clear();
    return nullptr;
  }
  Model *m = new Model();
  m->model = model;
  m->tensors = PyList_New(0);
  return reinterpret_cast<ffc_model_t *>(m);
}

void ffc_model_destroy(ffc_model_t *handle) {
  if (!handle) return;
  Model *m = reinterpret_cast<Model *>(handle);
  {
    Gil gil;
    Py_XDECREF(m->model);
    Py_XDECREF(m->tensors);
    Py_XDECREF(m->rng);
  }
  delete m;
}

int64_t ffc_model_input(ffc_model_t *handle, const int64_t *dims,
                        int32_t ndims, const char *name) {
  Model *m = reinterpret_cast<Model *>(handle);
  Gil gil;
  PyObject *shape = PyTuple_New(ndims);
  for (int32_t i = 0; i < ndims; ++i)
    PyTuple_SetItem(shape, i, PyLong_FromLongLong(dims[i]));
  PyObject *t = call_named(m->model, "create_tensor",
                           Py_BuildValue("(O)", shape), name);
  Py_DECREF(shape);
  if (!t) report_and_clear();
  return push_tensor(m, t);
}

int64_t ffc_model_dense(ffc_model_t *handle, int64_t input, int32_t out_dim,
                        const char *activation, const char *name) {
  Model *m = reinterpret_cast<Model *>(handle);
  Gil gil;
  PyObject *in = get_tensor(m, input);
  if (!in) return -1;
  PyObject *acti_cls = import_attr("flexflow_tpu.core.types", "ActiMode");
  if (!acti_cls) {
    report_and_clear();
    return -1;
  }
  PyObject *acti = PyObject_CallFunction(
      acti_cls, "s", activation && *activation ? activation : "none");
  Py_DECREF(acti_cls);
  if (!acti) {
    report_and_clear();
    return -1;
  }
  PyObject *t = call_named(m->model, "dense",
                           Py_BuildValue("(OiO)", in, out_dim, acti), name);
  Py_DECREF(acti);
  if (!t) report_and_clear();
  return push_tensor(m, t);
}

int64_t ffc_model_mha(ffc_model_t *handle, int64_t query, int64_t key,
                      int64_t value, int32_t embed_dim, int32_t num_heads,
                      const char *name) {
  Model *m = reinterpret_cast<Model *>(handle);
  Gil gil;
  PyObject *q = get_tensor(m, query);
  PyObject *k = get_tensor(m, key);
  PyObject *v = get_tensor(m, value);
  if (!q || !k || !v) return -1;
  PyObject *t = call_named(
      m->model, "multihead_attention",
      Py_BuildValue("(OOOii)", q, k, v, embed_dim, num_heads), name);
  if (!t) report_and_clear();
  return push_tensor(m, t);
}

int64_t ffc_model_softmax(ffc_model_t *handle, int64_t input,
                          const char *name) {
  Model *m = reinterpret_cast<Model *>(handle);
  Gil gil;
  PyObject *in = get_tensor(m, input);
  if (!in) return -1;
  PyObject *t = call_named(m->model, "softmax", Py_BuildValue("(O)", in), name);
  if (!t) report_and_clear();
  return push_tensor(m, t);
}

int64_t ffc_model_call(ffc_model_t *handle, const char *method,
                       const char *json_args) {
  // Generic builder: any FFModel layer method, args JSON-encoded, tensor
  // handles as {"__tensor__": id}. One C entry covers the ~60-builder
  // surface the reference's flexflow_c.cc wrapped function-by-function
  // (1937 LoC of hand glue) — the embedded interpreter gives it to us
  // reflectively. Multi-output builders (top_k, split, ...) push every
  // output; the returned id is the FIRST, the rest follow consecutively.
  Model *m = reinterpret_cast<Model *>(handle);
  Gil gil;
  PyObject *jsonmod = PyImport_ImportModule("json");
  if (!jsonmod) {
    report_and_clear();
    return -1;
  }
  PyObject *parsed = PyObject_CallMethod(jsonmod, "loads", "s",
                                         json_args ? json_args : "{}");
  Py_DECREF(jsonmod);
  if (!parsed) {
    report_and_clear();
    return -1;
  }
  PyObject *args_list = PyDict_GetItemString(parsed, "args");      // borrowed
  PyObject *kwargs_in = PyDict_GetItemString(parsed, "kwargs");    // borrowed

  // resolve {"__tensor__": id} placeholders (recursively for lists)
  struct Resolver {
    Model *m;
    PyObject *resolve(PyObject *v) {  // returns NEW reference
      if (PyDict_Check(v)) {
        PyObject *tid = PyDict_GetItemString(v, "__tensor__");
        if (tid) {
          int64_t id = PyLong_AsLongLong(tid);
          PyObject *t = get_tensor(m, id);
          if (t) {
            Py_INCREF(t);
          } else {
            // every other failure mode prints a traceback; a stale
            // tensor id must be diagnosable too
            PyErr_Format(PyExc_IndexError,
                         "ffc_model_call: invalid tensor id %lld",
                         (long long)id);
          }
          return t;
        }
      }
      if (PyList_Check(v)) {
        PyObject *out = PyList_New(PyList_Size(v));
        for (Py_ssize_t i = 0; i < PyList_Size(v); ++i) {
          PyObject *r = resolve(PyList_GetItem(v, i));
          if (!r) {
            Py_DECREF(out);
            return nullptr;
          }
          PyList_SetItem(out, i, r);
        }
        return out;
      }
      Py_INCREF(v);
      return v;
    }
  } R{m};

  Py_ssize_t nargs = args_list && PyList_Check(args_list) ? PyList_Size(args_list) : 0;
  PyObject *args = PyTuple_New(nargs);
  bool ok = true;
  for (Py_ssize_t i = 0; i < nargs; ++i) {
    PyObject *r = R.resolve(PyList_GetItem(args_list, i));
    if (!r) {
      ok = false;
      break;
    }
    PyTuple_SetItem(args, i, r);
  }
  PyObject *kwargs = PyDict_New();
  if (ok && kwargs_in && PyDict_Check(kwargs_in)) {
    PyObject *k, *v;
    Py_ssize_t pos = 0;
    while (PyDict_Next(kwargs_in, &pos, &k, &v)) {
      PyObject *r = R.resolve(v);
      if (!r) {
        ok = false;
        break;
      }
      PyDict_SetItem(kwargs, k, r);
      Py_DECREF(r);
    }
  }
  int64_t result = -1;
  if (ok) {
    PyObject *fn = PyObject_GetAttrString(m->model, method);
    PyObject *out = fn ? PyObject_Call(fn, args, kwargs) : nullptr;
    Py_XDECREF(fn);
    if (out) {
      if (PyTuple_Check(out) || PyList_Check(out)) {
        PyObject *seq = PySequence_Fast(out, "builder output");
        Py_ssize_t nout = PySequence_Fast_GET_SIZE(seq);
        for (Py_ssize_t i = 0; i < nout; ++i) {
          PyObject *t = PySequence_Fast_GET_ITEM(seq, i);
          Py_INCREF(t);
          int64_t id = push_tensor(m, t);
          if (i == 0) result = id;
        }
        Py_DECREF(seq);
        Py_DECREF(out);
      } else {
        result = push_tensor(m, out);
      }
    }
  }
  if (result < 0) report_and_clear();
  Py_DECREF(args);
  Py_DECREF(kwargs);
  Py_DECREF(parsed);
  return result;
}

int32_t ffc_model_compile(ffc_model_t *handle, double learning_rate,
                          const char *loss_type) {
  Model *m = reinterpret_cast<Model *>(handle);
  Gil gil;
  PyObject *opt_cls = import_attr("flexflow_tpu.runtime.optimizers", "SGDOptimizer");
  PyObject *loss_cls = import_attr("flexflow_tpu.core.types", "LossType");
  PyObject *jax_random = PyImport_ImportModule("jax.random");
  if (!opt_cls || !loss_cls || !jax_random) {
    report_and_clear();
    Py_XDECREF(opt_cls);
    Py_XDECREF(loss_cls);
    Py_XDECREF(jax_random);
    return -1;
  }
  PyObject *empty = PyTuple_New(0);
  PyObject *okw = Py_BuildValue("{s:d}", "lr", learning_rate);
  PyObject *opt = PyObject_Call(opt_cls, empty, okw);
  PyObject *loss = PyObject_CallFunction(loss_cls, "s", loss_type);
  int32_t rc = -1;
  if (opt && loss) {
    PyObject *compile_fn = PyObject_GetAttrString(m->model, "compile");
    if (compile_fn) {
      PyObject *kw =
          Py_BuildValue("{s:O,s:O}", "optimizer", opt, "loss_type", loss);
      PyObject *r = PyObject_Call(compile_fn, empty, kw);
      Py_XDECREF(kw);
      Py_DECREF(compile_fn);
      if (r) {
        Py_DECREF(r);
        m->rng = PyObject_CallMethod(jax_random, "key", "i", 0);
        m->compiled = m->rng != nullptr;
        rc = m->compiled ? 0 : -1;
      }
    }
  }
  if (rc != 0) report_and_clear();
  Py_XDECREF(opt);
  Py_XDECREF(loss);
  Py_DECREF(okw);
  Py_DECREF(empty);
  Py_DECREF(opt_cls);
  Py_DECREF(loss_cls);
  Py_DECREF(jax_random);
  return rc;
}

double ffc_model_fit_step(ffc_model_t *handle, const double *x,
                          const int64_t *x_shape, int32_t x_ndims,
                          const double *y, const int64_t *y_shape,
                          int32_t y_ndims, int32_t y_is_labels) {
  Model *m = reinterpret_cast<Model *>(handle);
  Gil gil;
  if (!m->compiled) return -1.0;
  PyObject *xa = array_from(x, x_shape, x_ndims, false);
  PyObject *ya = array_from(y, y_shape, y_ndims, y_is_labels != 0);
  if (!xa || !ya) {
    report_and_clear();
    Py_XDECREF(xa);
    Py_XDECREF(ya);
    return -1.0;
  }
  PyObject *executor = PyObject_GetAttrString(m->model, "executor");
  PyObject *inputs = PyList_New(1);
  Py_INCREF(xa);
  PyList_SetItem(inputs, 0, xa);
  PyObject *mets = executor ? PyObject_CallMethod(executor, "train_batch",
                                                  "OOO", inputs, ya, m->rng)
                            : nullptr;
  double loss = -1.0;
  if (mets) {
    PyObject *key = PyUnicode_FromString("loss");
    PyObject *l = key ? PyObject_GetItem(mets, key) : nullptr;
    Py_XDECREF(key);
    if (l) {
      PyObject *f = PyNumber_Float(l);
      if (f) {
        loss = PyFloat_AsDouble(f);
        Py_DECREF(f);
      }
      Py_DECREF(l);
    }
    Py_DECREF(mets);
  }
  if (loss < 0 && PyErr_Occurred()) report_and_clear();
  Py_XDECREF(executor);
  Py_DECREF(inputs);
  Py_DECREF(xa);
  Py_DECREF(ya);
  return loss;
}

int64_t ffc_model_predict(ffc_model_t *handle, const double *x,
                          const int64_t *x_shape, int32_t x_ndims,
                          double *out, int64_t out_capacity,
                          int64_t *out_shape, int32_t *out_ndims) {
  // Forward pass on one input batch; flattens the first model output
  // into the caller's float64 buffer. Returns the element count written,
  // or -1 on error / insufficient capacity.
  Model *m = reinterpret_cast<Model *>(handle);
  Gil gil;
  if (!m->compiled) return -1;
  PyObject *xa = array_from(x, x_shape, x_ndims, false);
  if (!xa) {
    report_and_clear();
    return -1;
  }
  PyObject *executor = PyObject_GetAttrString(m->model, "executor");
  PyObject *inputs = PyList_New(1);
  Py_INCREF(xa);
  PyList_SetItem(inputs, 0, xa);
  PyObject *outs = executor
                       ? PyObject_CallMethod(executor, "predict", "O", inputs)
                       : nullptr;
  int64_t written = -1;
  if (outs && PySequence_Check(outs) && PySequence_Size(outs) > 0) {
    PyObject *first = PySequence_GetItem(outs, 0);
    PyObject *np = PyImport_ImportModule("numpy");
    // bulk copy through tobytes() — no per-element Python objects on the
    // inference hot path (mirror of array_from's frombuffer direction)
    PyObject *arr = np ? PyObject_CallMethod(np, "ascontiguousarray", "Os", first, "float64") : nullptr;
    PyObject *bytes = arr ? PyObject_CallMethod(arr, "tobytes", nullptr) : nullptr;
    char *buf = nullptr;
    Py_ssize_t blen = 0;
    if (bytes && PyBytes_AsStringAndSize(bytes, &buf, &blen) == 0) {
      int64_t n = blen / (Py_ssize_t)sizeof(double);
      if (n <= out_capacity) {
        std::memcpy(out, buf, (size_t)blen);
        written = n;
        if (out_shape && out_ndims) {
          PyObject *shp = PyObject_GetAttrString(arr, "shape");
          int32_t nd = static_cast<int32_t>(PyTuple_Size(shp));
          for (int32_t i = 0; i < nd && i < *out_ndims; ++i)
            out_shape[i] = PyLong_AsLongLong(PyTuple_GetItem(shp, i));
          *out_ndims = nd;
          Py_DECREF(shp);
        }
      }
    }
    Py_XDECREF(bytes);
    Py_XDECREF(arr);
    Py_XDECREF(np);
    Py_XDECREF(first);
  }
  if (written < 0) report_and_clear();
  Py_XDECREF(outs);
  Py_XDECREF(executor);
  Py_DECREF(inputs);
  Py_DECREF(xa);
  return written;
}

}  // extern "C"
