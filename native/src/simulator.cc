// Event-driven task-graph simulator.
//
// Native mirror of flexflow_tpu/search/simulator.py::_simulate, itself
// modeled on the reference's simulate_runtime (src/runtime/simulator.cc:856):
// dependency-ordered replay with per-device serialization. Ties broken by
// (ready_time, task id) exactly like the Python heap so both backends
// produce identical makespans.

#include <algorithm>
#include <queue>
#include <unordered_map>

#include "ffcore.h"
#include "ffcore_internal.h"

namespace ffcore {

double simulate_taskgraph(TaskGraph &tg) {
  const int64_t n = (int64_t)tg.tasks.size();
  std::vector<int64_t> counter(n);
  std::vector<double> ready_time(n, 0.0);
  using Item = std::pair<double, int64_t>;  // (ready_time, id)
  std::priority_queue<Item, std::vector<Item>, std::greater<Item>> ready;
  for (int64_t i = 0; i < n; i++) {
    counter[i] = tg.tasks[i].n_deps;
    if (counter[i] == 0) ready.push({0.0, i});
  }
  std::unordered_map<int64_t, double> device_free;
  double finish_all = 0.0;
  int64_t done = 0;
  while (!ready.empty()) {
    auto [rt, i] = ready.top();
    ready.pop();
    const Task &t = tg.tasks[i];
    double start = rt;
    if (t.device >= 0) {
      auto it = device_free.find(t.device);
      double free_at = it == device_free.end() ? 0.0 : it->second;
      start = std::max(rt, free_at);
    }
    double end = start + t.run_time;
    if (t.device >= 0) device_free[t.device] = end;
    finish_all = std::max(finish_all, end);
    done++;
    for (int64_t j : t.next) {
      counter[j]--;
      ready_time[j] = std::max(ready_time[j], end);
      if (counter[j] == 0) ready.push({ready_time[j], j});
    }
  }
  if (done != n) return -1.0;  // deadlock (cycle)
  return finish_all;
}

}  // namespace ffcore

extern "C" {

ffc_taskgraph_t *ffc_taskgraph_create(void) { return new ffc_taskgraph(); }

void ffc_taskgraph_destroy(ffc_taskgraph_t *tg) { delete tg; }

int64_t ffc_taskgraph_add_task(ffc_taskgraph_t *tg, int32_t kind,
                               int64_t device, double run_time) {
  tg->tasks.push_back({kind, device, run_time, {}, 0});
  return (int64_t)tg->tasks.size() - 1;
}

int64_t ffc_taskgraph_add_tasks(ffc_taskgraph_t *tg, int64_t n,
                                const int32_t *kinds, const int64_t *devices,
                                const double *run_times) {
  int64_t first = (int64_t)tg->tasks.size();
  tg->tasks.reserve(tg->tasks.size() + (size_t)n);
  for (int64_t i = 0; i < n; i++)
    tg->tasks.push_back({kinds[i], devices[i], run_times[i], {}, 0});
  return first;
}

int32_t ffc_taskgraph_add_dep(ffc_taskgraph_t *tg, int64_t src, int64_t dst) {
  int64_t n = (int64_t)tg->tasks.size();
  if (src < 0 || dst < 0 || src >= n || dst >= n) return -1;
  tg->tasks[src].next.push_back(dst);
  tg->tasks[dst].n_deps++;
  return 0;
}

int32_t ffc_taskgraph_add_deps(ffc_taskgraph_t *tg, int64_t n,
                               const int64_t *srcs, const int64_t *dsts) {
  for (int64_t i = 0; i < n; i++)
    if (ffc_taskgraph_add_dep(tg, srcs[i], dsts[i]) != 0) return -1;
  return 0;
}

int64_t ffc_taskgraph_num_tasks(const ffc_taskgraph_t *tg) {
  return (int64_t)tg->tasks.size();
}

double ffc_taskgraph_simulate(ffc_taskgraph_t *tg) {
  return ffcore::simulate_taskgraph(*tg);
}

const char *ffc_version(void) { return "ffcore 0.1.0"; }

}  // extern "C"
