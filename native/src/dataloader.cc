// Dataloader kernels: multithreaded batch row-gather and deterministic
// index shuffling.
//
// Reference: SingleDataLoader (python/flexflow_dataloader.cc:34+) keeps
// the full dataset in host DRAM and issues per-batch index load tasks;
// the CUDA copy kernels become plain parallel memcpy on the host here —
// the host->TPU transfer itself is jax.device_put on the gathered batch.

#include <algorithm>
#include <cstring>
#include <thread>
#include <vector>

#include "ffcore.h"

namespace {

inline uint64_t splitmix64(uint64_t &state) {
  uint64_t z = (state += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

int32_t ffc_batch_gather(const void *src, void *dst, const int64_t *idx,
                         int64_t n_rows, int64_t row_bytes,
                         int32_t num_threads) {
  if (!src || !dst || !idx || n_rows < 0 || row_bytes <= 0) return -1;
  const char *s = (const char *)src;
  char *d = (char *)dst;
  int32_t hw = (int32_t)std::thread::hardware_concurrency();
  if (num_threads <= 0) num_threads = hw > 0 ? hw : 4;
  // not worth spawning threads for small batches
  if (n_rows * row_bytes < (1 << 20) || num_threads == 1) {
    for (int64_t i = 0; i < n_rows; i++)
      std::memcpy(d + i * row_bytes, s + idx[i] * row_bytes, (size_t)row_bytes);
    return 0;
  }
  num_threads = (int32_t)std::min<int64_t>(num_threads, n_rows);
  std::vector<std::thread> workers;
  int64_t chunk = (n_rows + num_threads - 1) / num_threads;
  for (int32_t t = 0; t < num_threads; t++) {
    int64_t lo = t * chunk, hi = std::min(n_rows, lo + chunk);
    if (lo >= hi) break;
    workers.emplace_back([=]() {
      for (int64_t i = lo; i < hi; i++)
        std::memcpy(d + i * row_bytes, s + idx[i] * row_bytes,
                    (size_t)row_bytes);
    });
  }
  for (auto &w : workers) w.join();
  return 0;
}

void ffc_shuffle_indices(int64_t *idx, int64_t n, uint64_t seed) {
  uint64_t state = seed;
  for (int64_t i = n - 1; i > 0; i--) {
    int64_t j = (int64_t)(splitmix64(state) % (uint64_t)(i + 1));
    std::swap(idx[i], idx[j]);
  }
}

}  // extern "C"
