// Internal C++ structures behind the ffcore C API.
#ifndef FFCORE_INTERNAL_H
#define FFCORE_INTERNAL_H

#include <cmath>
#include <cstdint>
#include <limits>
#include <map>
#include <utility>
#include <vector>

namespace ffcore {

// ---------------------------------------------------------------- taskgraph
struct Task {
  int32_t kind;
  int64_t device;  // -1: unbound (comm edge)
  double run_time;
  std::vector<int64_t> next;
  int64_t n_deps = 0;  // static in-degree
};

struct TaskGraph {
  std::vector<Task> tasks;
};

// ------------------------------------------------------------ machine model
// Mirrors flexflow_tpu/search/machine_model.py semantics exactly so the
// Python and native paths agree bit-for-bit on schedule decisions.
struct MachineModel {
  enum Kind { SIMPLE, NETWORKED } kind;

  // shared
  int32_t num_nodes = 1;
  int32_t devices_per_node = 1;
  double ici_latency = 1e-6, ici_bandwidth = 100e9;

  // simple
  double dcn_latency = 10e-6, dcn_bandwidth = 25e9;

  // networked
  int32_t num_switches = 0;
  std::vector<int32_t> conn;  // (E x E) link multiplicity, E = nodes+switches
  double link_latency = 10e-6, link_bandwidth = 25e9;
  int32_t routing = 1;  // 0 shortest, 1 weighted shortest, 2 ecmp
  int32_t ecmp_max_paths = 4;
  std::map<std::pair<int32_t, int32_t>, std::vector<std::vector<int32_t>>>
      route_cache;

  int32_t num_endpoints() const { return num_nodes + num_switches; }
  int32_t num_devices() const { return num_nodes * devices_per_node; }
  int32_t node_of(int32_t dev) const { return dev / devices_per_node; }
  int32_t links(int32_t u, int32_t v) const {
    return conn[(size_t)u * num_endpoints() + v];
  }

  const std::vector<std::vector<int32_t>> &routes(int32_t src_node,
                                                  int32_t dst_node);
  double comm_time(int32_t src_dev, int32_t dst_dev, double nbytes);
};

double simulate_taskgraph(TaskGraph &tg);

double allreduce_simulate(MachineModel &mm, const int32_t *participants,
                          int32_t n, double nbytes, int32_t pattern);

}  // namespace ffcore

struct ffc_taskgraph : ffcore::TaskGraph {};
struct ffc_machine_model : ffcore::MachineModel {};

#endif  // FFCORE_INTERNAL_H
