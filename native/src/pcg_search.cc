// PCG + DP machine-view search, native (C API).
//
// Reference analog: the C API (python/flexflow_c.h) exposes the C++
// model/search engine to any host language; here ffc_pcg_* exposes the
// framework's view-assignment search natively. The caller supplies each
// op's cost primitives (flops, HBM bytes, weight bytes, output bytes) —
// the op-library math stays host-side — and the native engine runs the
// memoized sequential-split DP over candidate shard degrees with
// roofline compute times, gradient-allreduce costs from the machine
// model, and boundary-reshard charges (mirror of
// flexflow_tpu/search/dp_search.py SearchHelper; reference:
// SearchHelper graph.cc:115+, find_optimal_sequence_graph_time).
#include "../include/ffcore.h"
#include "ffcore_internal.h"

#include <algorithm>
#include <cstring>
#include <string>

namespace ffcore {

struct PcgOp {
  double flops = 0.0;        // fwd FLOPs (bwd charged at 2x)
  double bytes = 0.0;        // HBM bytes touched fwd
  double weight_bytes = 0.0; // parameter bytes (allreduce per step)
  double output_bytes = 0.0; // boundary tensor size (reshard charge)
  std::string name;
  std::vector<int64_t> inputs;
  // hybrid-candidate structural attributes (unity.py proposer aggregates)
  int32_t repeat_idx = -1;   // pipelined-block instance; -1 = outer
  int32_t is_attention = 0;  // ring-attention-capable
  double tp_shardable_bytes = 0.0;  // Megatron-shardable weight bytes
  int64_t tp_dim_size = 0;          // dim tp must divide
  int32_t pipe_tp_ok = 0;           // in-stage (pipeline) tp can shard it
};

struct Pcg {
  std::vector<PcgOp> ops;
  // chip model (set once per optimize call)
  double peak_flops = 197e12, mxu_eff = 0.55;
  double hbm_bw = 0.82e12, hbm_eff = 0.8;
  double overhead = 2e-6;
};

static double op_time(const Pcg &p, const PcgOp &op, int degree) {
  double t_c = (op.flops / degree) / (p.peak_flops * p.mxu_eff);
  double t_m = (op.bytes / degree) / (p.hbm_bw * p.hbm_eff);
  double fwd = std::max(t_c, t_m) + p.overhead;
  // fwd + bwd; bwd ~ 2x fwd for matmul-bound ops, ~1x for memory-bound
  // (exactly CostModel.op_cost_metrics' rule, cost_model.py)
  double bwd_factor = op.flops > 0.0 ? 2.0 : 1.0;
  return (1.0 + bwd_factor) * fwd;
}

static void link_params(MachineModel *mm, int n, double *lat, double *bw);

static double sync_time(MachineModel *mm, const PcgOp &op, int degree) {
  if (degree <= 1 || op.weight_bytes <= 0.0) return 0.0;
  // bandwidth-optimal ring over the view (matches CostModel.allreduce_time)
  double lat, bw;
  link_params(mm, degree, &lat, &bw);
  return 2.0 * (degree - 1) * lat +
         2.0 * (degree - 1) / degree * op.weight_bytes / bw;
}

static double reshard_time(MachineModel *mm, double nbytes, int degree) {
  if (degree <= 1 || nbytes <= 0.0) return 0.0;
  bool intra = degree <= mm->devices_per_node;
  double lat = intra ? mm->ici_latency : mm->dcn_latency;
  double bw = intra ? mm->ici_bandwidth : mm->dcn_bandwidth;
  return lat + nbytes / (bw * 0.85);
}

// inter-device link (latency, effective bandwidth) for an n-wide group,
// honoring the NETWORKED model's cross-node links like sync_time does
static void link_params(MachineModel *mm, int n, double *lat, double *bw) {
  bool intra = n <= mm->devices_per_node;
  *lat = intra ? mm->ici_latency : mm->dcn_latency;
  *bw = intra ? mm->ici_bandwidth : mm->dcn_bandwidth;
  if (mm->kind == MachineModel::NETWORKED && !intra) {
    *lat = mm->link_latency;
    *bw = mm->link_bandwidth;
  }
  *bw *= 0.85;
}

// point-to-point hop (CostModel.p2p_time: latency + bytes / effective bw)
static double p2p_time(MachineModel *mm, double nbytes) {
  double lat, bw;
  link_params(mm, 1, &lat, &bw);
  return lat + nbytes / bw;
}

// bandwidth-optimal ring allreduce over n devices (CostModel
// .allreduce_time). ``groups`` (independent group instances of the
// collective) is accepted for call-site symmetry with the Python
// predictor but NOT charged: the round-5 honest measurements showed
// concurrent group instances do not serialize (coll_groups_alpha=0 in
// the refitted host model), and real ICI runs them concurrently too.
static double ring_time(MachineModel *mm, double nbytes, int n,
                        int groups = 1) {
  (void)groups;
  if (n <= 1 || nbytes <= 0.0) return 0.0;
  double lat, bw;
  link_params(mm, n, &lat, &bw);
  return 2.0 * (n - 1) * lat + 2.0 * (n - 1) / n * nbytes / bw;
}

// every divisor of n >= lo, ascending (possibly EMPTY — degree 1 must
// not leak into the >= 2 proposer sweeps) — the reference instantiates
// xfers per divisor degree (substitution.cc:1726-1840)
static std::vector<int> divisor_degrees(int n, int lo) {
  std::vector<int> out;
  for (int d = lo; d <= n; ++d)
    if (n % d == 0) out.push_back(d);
  return out;
}

// divisors PLUS power-of-two sizes <= n: flat per-op degree scans keep
// partial-machine placements (degree 4 of 6 devices) alongside the
// divisor degrees (mirror of machine.py enumerate_machine_views)
static std::vector<int> flat_degrees(int n, int lo) {
  std::vector<int> out = divisor_degrees(n, lo);
  for (int d = 1; d <= n; d *= 2)
    if (d >= lo && n % d != 0) out.push_back(d);
  if (out.empty()) out.push_back(1);
  std::sort(out.begin(), out.end());
  return out;
}

// GPipe microbatch count (strategy.py default_microbatches)
static int default_microbatches(int batch, int pp, int dp) {
  const int prefs[3] = {4 * pp, 2 * pp, pp};
  for (int m : prefs)
    if (m <= batch && batch % (m * dp) == 0) return m;
  int hi = std::min(batch / std::max(1, dp), 4 * pp);
  for (int m = hi; m > 0; --m)
    if (batch % (m * dp) == 0) return m;
  return 1;
}

}  // namespace ffcore

using namespace ffcore;

extern "C" {

ffc_pcg_t *ffc_pcg_create(void) { return reinterpret_cast<ffc_pcg_t *>(new Pcg()); }

void ffc_pcg_destroy(ffc_pcg_t *pcg) { delete reinterpret_cast<Pcg *>(pcg); }

int64_t ffc_pcg_add_op(ffc_pcg_t *pcg, double flops, double bytes,
                       double weight_bytes, double output_bytes,
                       const char *name) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  PcgOp op;
  op.flops = flops;
  op.bytes = bytes;
  op.weight_bytes = weight_bytes;
  op.output_bytes = output_bytes;
  op.name = name ? name : "";
  p->ops.push_back(std::move(op));
  return static_cast<int64_t>(p->ops.size()) - 1;
}

int32_t ffc_pcg_add_edge(ffc_pcg_t *pcg, int64_t src, int64_t dst) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  if (src < 0 || dst < 0 || src >= (int64_t)p->ops.size() ||
      dst >= (int64_t)p->ops.size() || src == dst)
    return -1;
  p->ops[dst].inputs.push_back(src);
  return 0;
}

void ffc_pcg_set_chip(ffc_pcg_t *pcg, double peak_flops, double mxu_eff,
                      double hbm_bandwidth, double hbm_eff,
                      double per_op_overhead) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  p->peak_flops = peak_flops;
  p->mxu_eff = mxu_eff;
  p->hbm_bw = hbm_bandwidth;
  p->hbm_eff = hbm_eff;
  p->overhead = per_op_overhead;
}

double ffc_pcg_optimize(ffc_pcg_t *pcg, ffc_mm_t *mm_, int32_t batch,
                        int32_t max_degree, int32_t *out_degrees) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  MachineModel *mm = reinterpret_cast<MachineModel *>(mm_);
  const int64_t n = static_cast<int64_t>(p->ops.size());
  if (n == 0) return 0.0;
  int32_t num_devices = mm->num_nodes * mm->devices_per_node;
  if (max_degree <= 0 || max_degree > num_devices) max_degree = num_devices;

  // candidate divisor + power-of-two degrees dividing the batch
  std::vector<int> degrees;
  for (int d : flat_degrees(max_degree, 1))
    if (batch <= 0 || batch % d == 0) degrees.push_back(d);
  if (degrees.empty()) degrees.push_back(1);

  // Per-op best time for each degree; DP over topo order charging a
  // reshard when producer and consumer pick different degrees (the
  // sequential bottleneck split of graph.cc:115). Message passing is
  // exact on (in-)trees; on DAGs a producer shared by several consumers
  // has its subtree charged once per consumer (tree relaxation — the
  // branch-aware HORIZONTAL splits stay host-side where the full graph
  // lives). Backtracking keeps a PER-PRODUCER argmin table, so branchy
  // graphs recover a consistent assignment (round-2 review: a single
  // shared `prev` backpointer returned wrong assignments off the chain).
  const double INF = std::numeric_limits<double>::infinity();
  const size_t nd = degrees.size();
  std::vector<std::vector<double>> best(n, std::vector<double>(nd, INF));
  // prev[i][di * n_inputs + k] = argmin degree index of input k
  std::vector<std::vector<int>> prev(n);

  for (int64_t i = 0; i < n; ++i) {
    const PcgOp &op = p->ops[i];
    const size_t nin = op.inputs.size();
    prev[i].assign(nd * (nin ? nin : 1), 0);
    for (size_t di = 0; di < nd; ++di) {
      double total = op_time(*p, op, degrees[di]) + sync_time(mm, op, degrees[di]);
      for (size_t k = 0; k < nin; ++k) {
        int64_t src = op.inputs[k];
        double b = INF;
        int arg = 0;
        for (size_t dj = 0; dj < nd; ++dj) {
          double x = best[src][dj];
          if (dj != di)
            x += reshard_time(mm, p->ops[src].output_bytes,
                              std::max(degrees[di], degrees[dj]));
          if (x < b) {
            b = x;
            arg = static_cast<int>(dj);
          }
        }
        total += b;
        prev[i][di * nin + k] = arg;
      }
      best[i][di] = total;
    }
  }

  // consumers per op (to find every sink, not just the last op)
  std::vector<int> n_consumers(n, 0);
  for (int64_t i = 0; i < n; ++i)
    for (int64_t src : p->ops[i].inputs) n_consumers[src]++;

  // cost = sum over sinks (tree semantics; shared producers counted per
  // consuming sink); assignment backtracked from every sink, first
  // consumer in reverse topo order wins on shared producers
  double bcost = 0.0;
  std::vector<int> pick(n, -1);
  for (int64_t i = n - 1; i >= 0; --i) {
    if (n_consumers[i] != 0) continue;  // not a sink
    double b = INF;
    int bdeg = 0;
    for (size_t di = 0; di < nd; ++di)
      if (best[i][di] < b) {
        b = best[i][di];
        bdeg = static_cast<int>(di);
      }
    bcost += b;
    if (pick[i] < 0) pick[i] = bdeg;
  }
  for (int64_t i = n - 1; i >= 0; --i) {
    if (pick[i] < 0) continue;  // unreachable from any sink (shouldn't happen)
    const size_t nin = p->ops[i].inputs.size();
    for (size_t k = 0; k < nin; ++k) {
      int64_t src = p->ops[i].inputs[k];
      if (pick[src] < 0) pick[src] = prev[i][pick[i] * nin + k];
    }
  }
  if (out_degrees)
    for (int64_t i = 0; i < n; ++i)
      out_degrees[i] = degrees[pick[i] < 0 ? 0 : pick[i]];
  return bcost;
}

double ffc_pcg_uniform_best(ffc_pcg_t *pcg, ffc_mm_t *mm_, int32_t batch,
                            int32_t max_degree, int32_t *out_degree) {
  // One SHARED degree for the whole (sub)graph — exactly the Python
  // SearchHelper._leaf_cost scan (dp_search.py): per-op roofline at
  // n_parts=k plus per-weight ring allreduce, minimized over candidate
  // power-of-two degrees. This is the DP's leaf hot path; the Python
  // side uses it as a fast selector when its cost model is analytic.
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  MachineModel *mm = reinterpret_cast<MachineModel *>(mm_);
  const int64_t n = static_cast<int64_t>(p->ops.size());
  int32_t num_devices = mm->num_nodes * mm->devices_per_node;
  if (max_degree <= 0 || max_degree > num_devices) max_degree = num_devices;
  double bcost = std::numeric_limits<double>::infinity();
  int32_t bdeg = 1;
  for (int d : flat_degrees(max_degree, 1)) {
    if (batch > 0 && batch % d != 0) continue;
    double total = 0.0;
    for (int64_t i = 0; i < n; ++i) {
      total += op_time(*p, p->ops[i], d) + sync_time(mm, p->ops[i], d);
    }
    if (total < bcost) {
      bcost = total;
      bdeg = d;
    }
  }
  if (out_degree) *out_degree = bdeg;
  return bcost;
}

int32_t ffc_pcg_op_set_parallel_attrs(ffc_pcg_t *pcg, int64_t op,
                                      int32_t repeat_idx,
                                      int32_t is_attention,
                                      double tp_shardable_bytes,
                                      int64_t tp_dim_size,
                                      int32_t pipe_tp_ok) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  if (op < 0 || op >= (int64_t)p->ops.size()) return -1;
  PcgOp &o = p->ops[op];
  o.repeat_idx = repeat_idx;
  o.is_attention = is_attention;
  o.tp_shardable_bytes = tp_shardable_bytes;
  o.tp_dim_size = tp_dim_size;
  o.pipe_tp_ok = pipe_tp_ok;
  return 0;
}

int32_t ffc_pcg_propose_hybrid(ffc_pcg_t *pcg, ffc_mm_t *mm_, int32_t batch,
                               double boundary_bytes, int64_t seq_len,
                               double capacity, ffc_hybrid_t *out) {
  // Native mirror of unity.py's _propose_pipeline +
  // _propose_context_parallel + the feasible-cheapest-first winner walk
  // (reference: ONE search engine behind every API entry, graph.cc:2047
  // — a C caller must not get a strictly weaker search than Python).
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  MachineModel *mm = reinterpret_cast<MachineModel *>(mm_);
  if (!out) return -1;
  const int64_t n = static_cast<int64_t>(p->ops.size());
  const int N = mm->num_nodes * mm->devices_per_node;

  // ---- aggregates (what the Python proposers derive from the PCG)
  int R = 0;  // number of repeated-block instances
  double wbytes = 0.0, repeat_w = 0.0, outer_w = 0.0;
  double sharded_repeat = 0.0, sharded_all = 0.0;
  int n_attn_block = 0, n_attn_all = 0;
  double attn_act_bytes = 0.0;
  std::vector<int64_t> block0, outer, attn_ops;
  // SEPARATE tp inventories, as in unity.py: the pipeline proposer's
  // tp_divides consults only the repeated BLOCK's shardable dims (an
  // odd-vocab outer embedding must not veto pp x tp), while the cp
  // proposer consults the whole graph's megatron set
  std::vector<int64_t> block_tp_dims, all_tp_dims;
  bool block_shardable = false, all_shardable = false;
  bool block_dims_known = true, all_dims_known = true;
  for (int64_t i = 0; i < n; ++i) {
    const PcgOp &o = p->ops[i];
    wbytes += o.weight_bytes;
    bool in_repeat = o.repeat_idx >= 0;
    if (in_repeat) {
      R = std::max(R, o.repeat_idx + 1);
      repeat_w += o.weight_bytes;
      if (o.pipe_tp_ok) sharded_repeat += o.tp_shardable_bytes;
      if (o.repeat_idx == 0) {
        block0.push_back(i);
        if (o.is_attention) n_attn_block++;
      }
    } else {
      outer.push_back(i);
      outer_w += o.weight_bytes;
    }
    sharded_all += o.tp_shardable_bytes;
    if (o.tp_shardable_bytes > 0.0) {
      all_shardable = true;
      if (o.tp_dim_size > 0)
        all_tp_dims.push_back(o.tp_dim_size);
      else
        all_dims_known = false;
      if (in_repeat && o.pipe_tp_ok) {
        block_shardable = true;
        if (o.tp_dim_size > 0)
          block_tp_dims.push_back(o.tp_dim_size);
        else
          block_dims_known = false;
      }
    }
    if (o.is_attention) {
      n_attn_all++;
      attn_ops.push_back(i);
      if (attn_act_bytes <= 0.0) attn_act_bytes = o.output_bytes;
    }
  }
  double repl_repeat = std::max(0.0, repeat_w - sharded_repeat);
  double repl_all = std::max(0.0, wbytes - sharded_all);
  auto divides_all = [](const std::vector<int64_t> &dims, int t) {
    for (int64_t d : dims)
      if (d % t != 0) return false;
    return true;
  };
  auto block_tp_divides = [&](int t) {
    return block_shardable && block_dims_known && divides_all(block_tp_dims, t);
  };
  auto all_tp_divides = [&](int t) {
    return all_shardable && all_dims_known && divides_all(all_tp_dims, t);
  };

  const double INF = std::numeric_limits<double>::infinity();
  ffc_hybrid_t best_dp{0, 1, 1, 1, 1, 1, INF, 4.0 * wbytes};
  ffc_hybrid_t cand;
  std::vector<ffc_hybrid_t> cands;

  // ---- dp baseline: one shared degree (weights replicate)
  for (int d : flat_degrees(N, 1)) {
    if (batch > 0 && batch % d != 0) continue;
    double total = 0.0;
    for (int64_t i = 0; i < n; ++i)
      total += op_time(*p, p->ops[i], d) + sync_time(mm, p->ops[i], d);
    if (total < best_dp.cost) {
      best_dp.cost = total;
      best_dp.dp = d;
    }
  }
  cands.push_back(best_dp);

  // ---- pipeline candidates (pp x tp x cp; unity._propose_pipeline)
  if (R >= 2 && batch >= 2 && !block0.empty()) {
    for (int pp : divisor_degrees(N, 2)) {
      if (pp > R || R % pp != 0) continue;
      std::vector<int> tps = divisor_degrees(N / pp, 2);
      tps.insert(tps.begin(), 1);
      for (int tp : tps) {
        if ((N / pp) % tp != 0) continue;
        if (tp > 1 && !block_tp_divides(tp)) continue;
        std::vector<int> cps = divisor_degrees(N / (pp * tp), 2);
        cps.insert(cps.begin(), 1);
        for (int cp : cps) {
          if ((N / (pp * tp)) % cp != 0) continue;
          if (cp > 1 && (n_attn_block == 0 || seq_len <= 0 || seq_len % cp != 0))
            continue;
          int dp_eff = N / (pp * tp * cp);
          if (dp_eff < 1 || batch % std::max(1, dp_eff) != 0) continue;
          int M = default_microbatches(batch, pp, dp_eff);
          int act_parts = dp_eff * M * cp;
          double block_t = 0.0;
          for (int64_t i : block0) {
            const PcgOp &o = p->ops[i];
            int parts = act_parts *
                        (o.pipe_tp_ok && o.tp_shardable_bytes > 0.0 ? tp : 1);
            block_t += op_time(*p, o, parts);
          }
          double stage_t = block_t * (R / pp);
          int ticks = M + pp - 1;
          double pt = p2p_time(mm, boundary_bytes / std::max(1, act_parts));
          double coll = 0.0;
          if (tp > 1)
            coll += 4.0 * (R / pp) *
                    ring_time(mm, boundary_bytes / std::max(1, act_parts), tp,
                              dp_eff * cp);
          if (cp > 1)
            coll += 4.0 * (R / pp) * n_attn_block * (cp - 1) *
                    p2p_time(mm, 2.0 * boundary_bytes / std::max(1, act_parts));
          double outer_t = 0.0;
          for (int64_t i : outer)
            outer_t += op_time(*p, p->ops[i], std::max(1, dp_eff));
          double per_dev_w = sharded_repeat / (pp * tp) + repl_repeat / pp;
          double sync = ring_time(mm, per_dev_w, dp_eff * cp) +
                        ring_time(mm, outer_w, N);
          cand = ffc_hybrid_t{1, dp_eff, pp, tp, cp, M,
                              ticks * (stage_t + coll + pt) + outer_t + sync,
                              4.0 * (per_dev_w + outer_w) +
                                  boundary_bytes * (R / pp) /
                                      std::max(1, dp_eff * cp)};
          cands.push_back(cand);
        }
      }
    }
  }

  // ---- context-parallel candidates (dp x cp x tp;
  // unity._propose_context_parallel)
  if (n_attn_all > 0 && seq_len > 0) {
    double base = 0.0;
    for (int64_t i = 0; i < n; ++i) base += op_time(*p, p->ops[i], N);
    for (int cp : divisor_degrees(N, 2)) {
      if (cp > seq_len || seq_len % cp != 0) continue;
      std::vector<int> tps = divisor_degrees(N / cp, 2);
      tps.insert(tps.begin(), 1);
      for (int tp : tps) {
        if ((N / cp) % tp != 0) continue;
        if (tp > 1 && !all_tp_divides(tp)) continue;
        int dp = N / (cp * tp);
        if (dp < 1 || batch % std::max(1, dp) != 0) continue;
        double total = base;
        for (int64_t i : attn_ops)
          total += 2.0 * (cp - 1) *
                   p2p_time(mm, 2.0 * p->ops[i].output_bytes / std::max(1, N));
        double mem;
        if (tp > 1) {
          total += 4.0 * n_attn_all *
                   ring_time(mm, attn_act_bytes / std::max(1, dp * cp), tp,
                             dp * cp);
          total += ring_time(mm, sharded_all / tp, dp * cp);
          total += ring_time(mm, repl_all, N);
          mem = 4.0 * (sharded_all / tp + repl_all);
        } else {
          total += ring_time(mm, wbytes, N);
          mem = 4.0 * wbytes;
        }
        cand = ffc_hybrid_t{2, dp, 1, tp, cp, 1, total, mem};
        cands.push_back(cand);
      }
    }
  }

  // ---- feasible-cheapest-first winner walk (unity.py): under a known
  // capacity prefer the cheapest candidate that FITS; nothing fits ->
  // the dp baseline (its weights may shard further under the λ search)
  const ffc_hybrid_t *win = &best_dp;
  if (capacity > 0.0) {
    const ffc_hybrid_t *bf = nullptr;
    for (const ffc_hybrid_t &c : cands)
      if (c.mem_per_device <= capacity && (!bf || c.cost < bf->cost)) bf = &c;
    if (bf) win = bf;
  } else {
    for (const ffc_hybrid_t &c : cands)
      if (c.cost < win->cost) win = &c;
  }
  *out = *win;
  return 0;
}

}  // extern "C"
