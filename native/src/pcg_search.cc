// PCG + DP machine-view search, native (C API).
//
// Reference analog: the C API (python/flexflow_c.h) exposes the C++
// model/search engine to any host language; here ffc_pcg_* exposes the
// framework's view-assignment search natively. The caller supplies each
// op's cost primitives (flops, HBM bytes, weight bytes, output bytes) —
// the op-library math stays host-side — and the native engine runs the
// memoized sequential-split DP over candidate shard degrees with
// roofline compute times, gradient-allreduce costs from the machine
// model, and boundary-reshard charges (mirror of
// flexflow_tpu/search/dp_search.py SearchHelper; reference:
// SearchHelper graph.cc:115+, find_optimal_sequence_graph_time).
#include "../include/ffcore.h"
#include "ffcore_internal.h"

#include <algorithm>
#include <cstring>
#include <string>

namespace ffcore {

struct PcgOp {
  double flops = 0.0;        // fwd FLOPs (bwd charged at 2x)
  double bytes = 0.0;        // HBM bytes touched fwd
  double weight_bytes = 0.0; // parameter bytes (allreduce per step)
  double output_bytes = 0.0; // boundary tensor size (reshard charge)
  std::string name;
  std::vector<int64_t> inputs;
};

struct Pcg {
  std::vector<PcgOp> ops;
  // chip model (set once per optimize call)
  double peak_flops = 197e12, mxu_eff = 0.55;
  double hbm_bw = 0.82e12, hbm_eff = 0.8;
  double overhead = 2e-6;
};

static double op_time(const Pcg &p, const PcgOp &op, int degree) {
  double t_c = (op.flops / degree) / (p.peak_flops * p.mxu_eff);
  double t_m = (op.bytes / degree) / (p.hbm_bw * p.hbm_eff);
  double fwd = std::max(t_c, t_m) + p.overhead;
  // fwd + bwd; bwd ~ 2x fwd for matmul-bound ops, ~1x for memory-bound
  // (exactly CostModel.op_cost_metrics' rule, cost_model.py)
  double bwd_factor = op.flops > 0.0 ? 2.0 : 1.0;
  return (1.0 + bwd_factor) * fwd;
}

static double sync_time(MachineModel *mm, const PcgOp &op, int degree) {
  if (degree <= 1 || op.weight_bytes <= 0.0) return 0.0;
  // bandwidth-optimal ring over the view (matches CostModel.allreduce_time)
  bool intra = degree <= mm->devices_per_node;
  double lat = intra ? mm->ici_latency : mm->dcn_latency;
  double bw = intra ? mm->ici_bandwidth : mm->dcn_bandwidth;
  if (mm->kind == MachineModel::NETWORKED && !intra) {
    lat = mm->link_latency;
    bw = mm->link_bandwidth;
  }
  return 2.0 * (degree - 1) * lat +
         2.0 * (degree - 1) / degree * op.weight_bytes / (bw * 0.85);
}

static double reshard_time(MachineModel *mm, double nbytes, int degree) {
  if (degree <= 1 || nbytes <= 0.0) return 0.0;
  bool intra = degree <= mm->devices_per_node;
  double lat = intra ? mm->ici_latency : mm->dcn_latency;
  double bw = intra ? mm->ici_bandwidth : mm->dcn_bandwidth;
  return lat + nbytes / (bw * 0.85);
}

}  // namespace ffcore

using namespace ffcore;

extern "C" {

ffc_pcg_t *ffc_pcg_create(void) { return reinterpret_cast<ffc_pcg_t *>(new Pcg()); }

void ffc_pcg_destroy(ffc_pcg_t *pcg) { delete reinterpret_cast<Pcg *>(pcg); }

int64_t ffc_pcg_add_op(ffc_pcg_t *pcg, double flops, double bytes,
                       double weight_bytes, double output_bytes,
                       const char *name) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  PcgOp op;
  op.flops = flops;
  op.bytes = bytes;
  op.weight_bytes = weight_bytes;
  op.output_bytes = output_bytes;
  op.name = name ? name : "";
  p->ops.push_back(std::move(op));
  return static_cast<int64_t>(p->ops.size()) - 1;
}

int32_t ffc_pcg_add_edge(ffc_pcg_t *pcg, int64_t src, int64_t dst) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  if (src < 0 || dst < 0 || src >= (int64_t)p->ops.size() ||
      dst >= (int64_t)p->ops.size() || src == dst)
    return -1;
  p->ops[dst].inputs.push_back(src);
  return 0;
}

void ffc_pcg_set_chip(ffc_pcg_t *pcg, double peak_flops, double mxu_eff,
                      double hbm_bandwidth, double hbm_eff,
                      double per_op_overhead) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  p->peak_flops = peak_flops;
  p->mxu_eff = mxu_eff;
  p->hbm_bw = hbm_bandwidth;
  p->hbm_eff = hbm_eff;
  p->overhead = per_op_overhead;
}

double ffc_pcg_optimize(ffc_pcg_t *pcg, ffc_mm_t *mm_, int32_t batch,
                        int32_t max_degree, int32_t *out_degrees) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  MachineModel *mm = reinterpret_cast<MachineModel *>(mm_);
  const int64_t n = static_cast<int64_t>(p->ops.size());
  if (n == 0) return 0.0;
  int32_t num_devices = mm->num_nodes * mm->devices_per_node;
  if (max_degree <= 0 || max_degree > num_devices) max_degree = num_devices;

  // candidate power-of-two degrees dividing the batch
  std::vector<int> degrees;
  for (int d = 1; d <= max_degree; d *= 2)
    if (batch <= 0 || batch % d == 0) degrees.push_back(d);
  if (degrees.empty()) degrees.push_back(1);

  // Per-op best time for each degree; DP over topo order charging a
  // reshard when producer and consumer pick different degrees (the
  // sequential bottleneck split of graph.cc:115). Message passing is
  // exact on (in-)trees; on DAGs a producer shared by several consumers
  // has its subtree charged once per consumer (tree relaxation — the
  // branch-aware HORIZONTAL splits stay host-side where the full graph
  // lives). Backtracking keeps a PER-PRODUCER argmin table, so branchy
  // graphs recover a consistent assignment (round-2 review: a single
  // shared `prev` backpointer returned wrong assignments off the chain).
  const double INF = std::numeric_limits<double>::infinity();
  const size_t nd = degrees.size();
  std::vector<std::vector<double>> best(n, std::vector<double>(nd, INF));
  // prev[i][di * n_inputs + k] = argmin degree index of input k
  std::vector<std::vector<int>> prev(n);

  for (int64_t i = 0; i < n; ++i) {
    const PcgOp &op = p->ops[i];
    const size_t nin = op.inputs.size();
    prev[i].assign(nd * (nin ? nin : 1), 0);
    for (size_t di = 0; di < nd; ++di) {
      double total = op_time(*p, op, degrees[di]) + sync_time(mm, op, degrees[di]);
      for (size_t k = 0; k < nin; ++k) {
        int64_t src = op.inputs[k];
        double b = INF;
        int arg = 0;
        for (size_t dj = 0; dj < nd; ++dj) {
          double x = best[src][dj];
          if (dj != di)
            x += reshard_time(mm, p->ops[src].output_bytes,
                              std::max(degrees[di], degrees[dj]));
          if (x < b) {
            b = x;
            arg = static_cast<int>(dj);
          }
        }
        total += b;
        prev[i][di * nin + k] = arg;
      }
      best[i][di] = total;
    }
  }

  // consumers per op (to find every sink, not just the last op)
  std::vector<int> n_consumers(n, 0);
  for (int64_t i = 0; i < n; ++i)
    for (int64_t src : p->ops[i].inputs) n_consumers[src]++;

  // cost = sum over sinks (tree semantics; shared producers counted per
  // consuming sink); assignment backtracked from every sink, first
  // consumer in reverse topo order wins on shared producers
  double bcost = 0.0;
  std::vector<int> pick(n, -1);
  for (int64_t i = n - 1; i >= 0; --i) {
    if (n_consumers[i] != 0) continue;  // not a sink
    double b = INF;
    int bdeg = 0;
    for (size_t di = 0; di < nd; ++di)
      if (best[i][di] < b) {
        b = best[i][di];
        bdeg = static_cast<int>(di);
      }
    bcost += b;
    if (pick[i] < 0) pick[i] = bdeg;
  }
  for (int64_t i = n - 1; i >= 0; --i) {
    if (pick[i] < 0) continue;  // unreachable from any sink (shouldn't happen)
    const size_t nin = p->ops[i].inputs.size();
    for (size_t k = 0; k < nin; ++k) {
      int64_t src = p->ops[i].inputs[k];
      if (pick[src] < 0) pick[src] = prev[i][pick[i] * nin + k];
    }
  }
  if (out_degrees)
    for (int64_t i = 0; i < n; ++i)
      out_degrees[i] = degrees[pick[i] < 0 ? 0 : pick[i]];
  return bcost;
}

double ffc_pcg_uniform_best(ffc_pcg_t *pcg, ffc_mm_t *mm_, int32_t batch,
                            int32_t max_degree, int32_t *out_degree) {
  // One SHARED degree for the whole (sub)graph — exactly the Python
  // SearchHelper._leaf_cost scan (dp_search.py): per-op roofline at
  // n_parts=k plus per-weight ring allreduce, minimized over candidate
  // power-of-two degrees. This is the DP's leaf hot path; the Python
  // side uses it as a fast selector when its cost model is analytic.
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  MachineModel *mm = reinterpret_cast<MachineModel *>(mm_);
  const int64_t n = static_cast<int64_t>(p->ops.size());
  int32_t num_devices = mm->num_nodes * mm->devices_per_node;
  if (max_degree <= 0 || max_degree > num_devices) max_degree = num_devices;
  double bcost = std::numeric_limits<double>::infinity();
  int32_t bdeg = 1;
  for (int d = 1; d <= max_degree; d *= 2) {
    if (batch > 0 && batch % d != 0) continue;
    double total = 0.0;
    for (int64_t i = 0; i < n; ++i) {
      total += op_time(*p, p->ops[i], d) + sync_time(mm, p->ops[i], d);
    }
    if (total < bcost) {
      bcost = total;
      bdeg = d;
    }
  }
  if (out_degree) *out_degree = bdeg;
  return bcost;
}

}  // extern "C"
