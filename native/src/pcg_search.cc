// PCG + DP machine-view search, native (C API).
//
// Reference analog: the C API (python/flexflow_c.h) exposes the C++
// model/search engine to any host language; here ffc_pcg_* exposes the
// framework's view-assignment search natively. The caller supplies each
// op's cost primitives (flops, HBM bytes, weight bytes, output bytes) —
// the op-library math stays host-side — and the native engine runs the
// memoized sequential-split DP over candidate shard degrees with
// roofline compute times, gradient-allreduce costs from the machine
// model, and boundary-reshard charges (mirror of
// flexflow_tpu/search/dp_search.py SearchHelper; reference:
// SearchHelper graph.cc:115+, find_optimal_sequence_graph_time).
#include "../include/ffcore.h"
#include "ffcore_internal.h"

#include <algorithm>
#include <cstring>
#include <string>

namespace ffcore {

struct PcgOp {
  double flops = 0.0;        // fwd FLOPs (bwd charged at 2x)
  double bytes = 0.0;        // HBM bytes touched fwd
  double weight_bytes = 0.0; // parameter bytes (allreduce per step)
  double output_bytes = 0.0; // boundary tensor size (reshard charge)
  std::string name;
  std::vector<int64_t> inputs;
};

struct Pcg {
  std::vector<PcgOp> ops;
  // chip model (set once per optimize call)
  double peak_flops = 197e12, mxu_eff = 0.55;
  double hbm_bw = 0.82e12, hbm_eff = 0.8;
  double overhead = 2e-6;
};

static double op_time(const Pcg &p, const PcgOp &op, int degree) {
  double t_c = (op.flops / degree) / (p.peak_flops * p.mxu_eff);
  double t_m = (op.bytes / degree) / (p.hbm_bw * p.hbm_eff);
  double fwd = std::max(t_c, t_m) + p.overhead;
  return 3.0 * fwd;  // fwd + ~2x bwd, same ratio as the Python cost model
}

static double sync_time(MachineModel *mm, const PcgOp &op, int degree) {
  if (degree <= 1 || op.weight_bytes <= 0.0) return 0.0;
  // bandwidth-optimal ring over the view (matches CostModel.allreduce_time)
  bool intra = degree <= mm->devices_per_node;
  double lat = intra ? mm->ici_latency : mm->dcn_latency;
  double bw = intra ? mm->ici_bandwidth : mm->dcn_bandwidth;
  if (mm->kind == MachineModel::NETWORKED && !intra) {
    lat = mm->link_latency;
    bw = mm->link_bandwidth;
  }
  return 2.0 * (degree - 1) * lat +
         2.0 * (degree - 1) / degree * op.weight_bytes / (bw * 0.85);
}

static double reshard_time(MachineModel *mm, double nbytes, int degree) {
  if (degree <= 1 || nbytes <= 0.0) return 0.0;
  bool intra = degree <= mm->devices_per_node;
  double lat = intra ? mm->ici_latency : mm->dcn_latency;
  double bw = intra ? mm->ici_bandwidth : mm->dcn_bandwidth;
  return lat + nbytes / (bw * 0.85);
}

}  // namespace ffcore

using namespace ffcore;

extern "C" {

ffc_pcg_t *ffc_pcg_create(void) { return reinterpret_cast<ffc_pcg_t *>(new Pcg()); }

void ffc_pcg_destroy(ffc_pcg_t *pcg) { delete reinterpret_cast<Pcg *>(pcg); }

int64_t ffc_pcg_add_op(ffc_pcg_t *pcg, double flops, double bytes,
                       double weight_bytes, double output_bytes,
                       const char *name) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  PcgOp op;
  op.flops = flops;
  op.bytes = bytes;
  op.weight_bytes = weight_bytes;
  op.output_bytes = output_bytes;
  op.name = name ? name : "";
  p->ops.push_back(std::move(op));
  return static_cast<int64_t>(p->ops.size()) - 1;
}

int32_t ffc_pcg_add_edge(ffc_pcg_t *pcg, int64_t src, int64_t dst) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  if (src < 0 || dst < 0 || src >= (int64_t)p->ops.size() ||
      dst >= (int64_t)p->ops.size() || src == dst)
    return -1;
  p->ops[dst].inputs.push_back(src);
  return 0;
}

void ffc_pcg_set_chip(ffc_pcg_t *pcg, double peak_flops, double mxu_eff,
                      double hbm_bandwidth, double hbm_eff,
                      double per_op_overhead) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  p->peak_flops = peak_flops;
  p->mxu_eff = mxu_eff;
  p->hbm_bw = hbm_bandwidth;
  p->hbm_eff = hbm_eff;
  p->overhead = per_op_overhead;
}

double ffc_pcg_optimize(ffc_pcg_t *pcg, ffc_mm_t *mm_, int32_t batch,
                        int32_t max_degree, int32_t *out_degrees) {
  Pcg *p = reinterpret_cast<Pcg *>(pcg);
  MachineModel *mm = reinterpret_cast<MachineModel *>(mm_);
  const int64_t n = static_cast<int64_t>(p->ops.size());
  if (n == 0) return 0.0;
  int32_t num_devices = mm->num_nodes * mm->devices_per_node;
  if (max_degree <= 0 || max_degree > num_devices) max_degree = num_devices;

  // candidate power-of-two degrees dividing the batch
  std::vector<int> degrees;
  for (int d = 1; d <= max_degree; d *= 2)
    if (batch <= 0 || batch % d == 0) degrees.push_back(d);
  if (degrees.empty()) degrees.push_back(1);

  // per-op best time for each degree; DP over topo order charging a
  // reshard when consecutive ops pick different degrees (the sequential
  // bottleneck split of graph.cc:115, specialized to chains — the
  // branch-aware splits stay host-side where the full graph lives)
  const double INF = std::numeric_limits<double>::infinity();
  std::vector<std::vector<double>> best(n, std::vector<double>(degrees.size(), INF));
  std::vector<std::vector<int>> prev(n, std::vector<int>(degrees.size(), 0));

  for (int64_t i = 0; i < n; ++i) {
    const PcgOp &op = p->ops[i];
    for (size_t di = 0; di < degrees.size(); ++di) {
      double t_here = op_time(*p, op, degrees[di]) + sync_time(mm, op, degrees[di]);
      if (op.inputs.empty()) {
        best[i][di] = t_here;
        continue;
      }
      // combine over producers: each contributes its best cost plus a
      // reshard if the degree changes at the boundary
      double total = t_here;
      for (int64_t src : op.inputs) {
        double b = INF;
        int arg = 0;
        for (size_t dj = 0; dj < degrees.size(); ++dj) {
          double x = best[src][dj];
          if (dj != di)
            x += reshard_time(mm, p->ops[src].output_bytes,
                              std::max(degrees[di], degrees[dj]));
          if (x < b) {
            b = x;
            arg = static_cast<int>(dj);
          }
        }
        total += b;
        prev[i][di] = arg;  // chain graphs: single producer dominates
      }
      best[i][di] = total;
    }
  }

  // the sink op's best assignment; backtrack the chain
  int64_t sink = n - 1;
  double bcost = INF;
  int bdeg = 0;
  for (size_t di = 0; di < degrees.size(); ++di)
    if (best[sink][di] < bcost) {
      bcost = best[sink][di];
      bdeg = static_cast<int>(di);
    }
  if (out_degrees) {
    std::vector<int> pick(n, bdeg);
    for (int64_t i = sink; i >= 0; --i) {
      if (!p->ops[i].inputs.empty()) {
        int64_t src = p->ops[i].inputs[0];
        pick[src] = prev[i][pick[i]];
      }
    }
    for (int64_t i = 0; i < n; ++i) out_degrees[i] = degrees[pick[i]];
  }
  return bcost;
}

}  // extern "C"
