/* ffcore — native runtime core for the flexflow_tpu framework.
 *
 * C API consumed by flexflow_tpu/_native via ctypes (the TPU-native
 * analog of the reference's C API python/flexflow_c.h: there C wraps the
 * C++ FFModel for Python cffi; here C wraps the native search/runtime
 * engine for the Python/JAX host).
 *
 * Subsystems (reference files they correspond to):
 *   - taskgraph simulator  : src/runtime/simulator.cc simulate_runtime
 *   - machine models       : src/runtime/machine_model.cc, network.cc
 *   - allreduce schedules  : fork AllreduceHelper simulator.h:614-651,
 *                            pattern generators simulator.cc:2870+
 *   - batch gather/shuffle : python/flexflow_dataloader.cc SingleDataLoader
 */
#ifndef FFCORE_H
#define FFCORE_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

const char *ffc_version(void);

/* ------------------------------------------------------------------ *
 * Task-graph simulator (event-driven, per-device serialization).
 * Task kinds mirror flexflow_tpu/search/simulator.py TASK_*.
 * ------------------------------------------------------------------ */
typedef struct ffc_taskgraph ffc_taskgraph_t;

ffc_taskgraph_t *ffc_taskgraph_create(void);
void ffc_taskgraph_destroy(ffc_taskgraph_t *tg);

/* Returns the new task id (dense, starting at 0). device -1 = unbound
 * (pure communication edge: no device serialization). */
int64_t ffc_taskgraph_add_task(ffc_taskgraph_t *tg, int32_t kind,
                               int64_t device, double run_time);
/* Bulk add; returns id of the first task added. */
int64_t ffc_taskgraph_add_tasks(ffc_taskgraph_t *tg, int64_t n,
                                const int32_t *kinds, const int64_t *devices,
                                const double *run_times);
/* 0 on success, -1 on bad ids. */
int32_t ffc_taskgraph_add_dep(ffc_taskgraph_t *tg, int64_t src, int64_t dst);
int32_t ffc_taskgraph_add_deps(ffc_taskgraph_t *tg, int64_t n,
                               const int64_t *srcs, const int64_t *dsts);

int64_t ffc_taskgraph_num_tasks(const ffc_taskgraph_t *tg);

/* Event-driven replay; returns makespan in seconds, or -1.0 if the
 * graph deadlocks (a dependency cycle). Destroys scheduling state but
 * the graph may be re-simulated (counters are rebuilt per call). */
double ffc_taskgraph_simulate(ffc_taskgraph_t *tg);

/* ------------------------------------------------------------------ *
 * Machine models.
 * ------------------------------------------------------------------ */
typedef struct ffc_machine_model ffc_mm_t;

/* Flat two-level model (reference: SimpleMachineModel
 * machine_model.cc:58): intra-node = ICI hop, inter-node = DCN hop. */
ffc_mm_t *ffc_mm_create_simple(int32_t num_nodes, int32_t devices_per_node,
                               double ici_latency, double ici_bandwidth,
                               double dcn_latency, double dcn_bandwidth);

/* Topology-aware model (fork: NetworkedMachineModel simulator.h:668-758).
 * conn: (num_nodes+num_switches)^2 row-major link-multiplicity matrix.
 * routing: 0 = shortest path (hop count), 1 = weighted shortest
 * (1/multiplicity edge weight), 2 = ECMP multi-path. */
ffc_mm_t *ffc_mm_create_networked(int32_t num_nodes, int32_t num_switches,
                                  int32_t devices_per_node,
                                  const int32_t *conn, double link_latency,
                                  double link_bandwidth, double ici_latency,
                                  double ici_bandwidth, int32_t routing,
                                  int32_t ecmp_max_paths);

void ffc_mm_destroy(ffc_mm_t *mm);
int32_t ffc_mm_num_devices(const ffc_mm_t *mm);

/* Seconds to move nbytes from device src to device dst. */
double ffc_mm_comm_time(ffc_mm_t *mm, int32_t src_dev, int32_t dst_dev,
                        double nbytes);

/* Routes between *nodes* (networked model only). Writes each path's
 * endpoint ids into out (row-major, max_len per row) and its length
 * into path_lens. Returns the number of paths (0 for same node or no
 * route; -1 if mm is not networked). */
int32_t ffc_mm_get_routes(ffc_mm_t *mm, int32_t src_node, int32_t dst_node,
                          int32_t *out, int32_t *path_lens, int32_t max_paths,
                          int32_t max_len);

/* ------------------------------------------------------------------ *
 * Allreduce schedule engine (fork parity).
 * pattern: 0 = ring, 1 = butterfly, 2 = double binary tree.
 * ------------------------------------------------------------------ */

/* Simulate one allreduce over the machine model as synchronized p2p
 * rounds; transfers sharing a physical link within a round congest
 * (mirror of LogicalTaskgraphSimulator.simulate_allreduce). */
double ffc_allreduce_simulate(ffc_mm_t *mm, const int32_t *participants,
                              int32_t n, double nbytes, int32_t pattern);

/* Evaluate all three patterns; writes times into out_times[3] (ring,
 * butterfly, dbt) and returns the argmin pattern id. */
int32_t ffc_allreduce_optimize(ffc_mm_t *mm, const int32_t *participants,
                               int32_t n, double nbytes, double *out_times);

/* ------------------------------------------------------------------ *
 * PCG + DP machine-view search (reference: the C API python/flexflow_c.h
 * exposes the model/search engine to host languages; SearchHelper DP
 * graph.cc:115+). The caller supplies per-op cost primitives; the
 * native engine assigns per-op shard degrees minimizing simulated step
 * time (roofline compute + ring-allreduce weight sync + boundary
 * reshard charges over the machine model).
 * ------------------------------------------------------------------ */
typedef struct ffc_pcg ffc_pcg_t;

ffc_pcg_t *ffc_pcg_create(void);
void ffc_pcg_destroy(ffc_pcg_t *pcg);

/* Returns the new op id (dense from 0; also its topo position — add ops
 * in topological order). */
int64_t ffc_pcg_add_op(ffc_pcg_t *pcg, double flops, double bytes,
                       double weight_bytes, double output_bytes,
                       const char *name);
int32_t ffc_pcg_add_edge(ffc_pcg_t *pcg, int64_t src, int64_t dst);

/* Chip roofline parameters (defaults: v5e-ish). */
void ffc_pcg_set_chip(ffc_pcg_t *pcg, double peak_flops, double mxu_eff,
                      double hbm_bandwidth, double hbm_eff,
                      double per_op_overhead);

/* Optimal per-op shard degrees over the machine model's devices.
 * batch bounds the degree (degree | batch); max_degree <= 0 means all
 * devices. out_degrees (len = num ops) receives the assignment; returns
 * the simulated step seconds of the best assignment. */
double ffc_pcg_optimize(ffc_pcg_t *pcg, ffc_mm_t *mm, int32_t batch,
                        int32_t max_degree, int32_t *out_degrees);

/* One SHARED degree for the whole graph (the DP leaf's uniform-view
 * scan, dp_search.py _leaf_cost): returns the best cost, *out_degree
 * receives the chosen divisor degree. */
double ffc_pcg_uniform_best(ffc_pcg_t *pcg, ffc_mm_t *mm, int32_t batch,
                            int32_t max_degree, int32_t *out_degree);

/* Structural attributes for hybrid (pipeline / context-parallel)
 * candidates (mirror of the aggregates unity.py's proposers derive from
 * the PCG): repeat_idx = which instance of the repeated block the op
 * belongs to (-1 = outside the pipelined stack), is_attention marks
 * ring-attention-capable ops, tp_shardable_bytes / tp_dim_size describe
 * the op's Megatron-shardable weights (tp must divide tp_dim_size), and
 * pipe_tp_ok marks ops the CONSERVATIVE in-stage tp lowering can shard
 * (complete column->row pairs) — pipeline candidates count only those
 * toward the sharded inventory, cp candidates count the full set.
 * Returns 0, or -1 on a bad op id. */
int32_t ffc_pcg_op_set_parallel_attrs(ffc_pcg_t *pcg, int64_t op,
                                      int32_t repeat_idx,
                                      int32_t is_attention,
                                      double tp_shardable_bytes,
                                      int64_t tp_dim_size,
                                      int32_t pipe_tp_ok);

typedef struct {
  int32_t kind; /* 0 = data parallel, 1 = pipeline, 2 = context parallel */
  int32_t dp;
  int32_t pp;
  int32_t tp;
  int32_t cp;
  int32_t n_microbatches;
  double cost;           /* modeled step seconds */
  double mem_per_device; /* modeled bytes (params+grads+moments+carry) */
} ffc_hybrid_t;

/* Hybrid winner across dp / pipeline(pp x tp x cp) / context-parallel
 * (dp x cp x tp) candidates with divisor-degree sweeps — the native
 * mirror of unity.py's _propose_pipeline + _propose_context_parallel +
 * feasible-cheapest-first winner walk (reference: one search engine for
 * every API entry, graph.cc:2047). boundary_bytes = rotating carry +
 * shared tensor bytes at the stage boundary; seq_len = block attention
 * sequence length (0 = none); capacity = per-device HBM bytes (<= 0:
 * unconstrained). Returns 0 and fills *out. */
int32_t ffc_pcg_propose_hybrid(ffc_pcg_t *pcg, ffc_mm_t *mm, int32_t batch,
                               double boundary_bytes, int64_t seq_len,
                               double capacity, ffc_hybrid_t *out);

/* ------------------------------------------------------------------ *
 * Full-model C API (reference: python/flexflow_c.h wraps FFModel for
 * host languages). Here the compute path is JAX/XLA, so these entry
 * points embed a CPython interpreter (like the reference's
 * python/main.cc) and drive the framework through it: a pure-C host
 * linking libffcore + libpython builds, unity-compiles, and trains a
 * model with no Python source of its own. The host process must have
 * flexflow_tpu importable (PYTHONPATH) and should set JAX_PLATFORMS.
 * ------------------------------------------------------------------ */
typedef struct ffc_model ffc_model_t;

ffc_model_t *ffc_model_create(int32_t batch_size, int32_t workers_per_node,
                              int32_t num_nodes, int32_t search_budget);
/* Full-config variant: config_json holds any FFConfig field by name
 * (e.g. {"batch_size":64,"pipeline_stages":2,"zero_optimizer":true,
 * "grad_accum_steps":4,"trace_window":8}) — every present and future
 * flag is reachable from C without new entry points. */
ffc_model_t *ffc_model_create_json(const char *config_json);
void ffc_model_destroy(ffc_model_t *model);

/* Tensor handles are dense int64 ids (-1 on error). */
int64_t ffc_model_input(ffc_model_t *model, const int64_t *dims,
                        int32_t ndims, const char *name);
/* activation: "none" | "relu" | "sigmoid" | "tanh" | "gelu" */
int64_t ffc_model_dense(ffc_model_t *model, int64_t input, int32_t out_dim,
                        const char *activation, const char *name);
int64_t ffc_model_mha(ffc_model_t *model, int64_t query, int64_t key,
                      int64_t value, int32_t embed_dim, int32_t num_heads,
                      const char *name);
int64_t ffc_model_softmax(ffc_model_t *model, int64_t input, const char *name);

/* Generic builder: call any FFModel layer method by name with
 * JSON-encoded arguments, e.g.
 *   ffc_model_call(m, "conv2d",
 *     "{\"args\": [{\"__tensor__\": 0}, 8, 3, 3, 1, 1, 1, 1],"
 *     " \"kwargs\": {\"name\": \"c1\"}}")
 * Tensor handles encode as {"__tensor__": id}. Multi-output builders
 * push every output tensor; the return value is the FIRST output's id
 * and the rest follow consecutively. Full surface parity with the
 * reference's per-function C wrappers (python/flexflow_c.cc). */
int64_t ffc_model_call(ffc_model_t *model, const char *method,
                       const char *json_args);

/* loss_type: "mean_squared_error" | "sparse_categorical_crossentropy" | ...
 * (core/types.py LossType values). Returns 0 on success. */
int32_t ffc_model_compile(ffc_model_t *model, double learning_rate,
                          const char *loss_type);

/* One optimizer step on (x, y); x is float64 row-major (cast to f32 on
 * the way in), y likewise — y_is_labels casts y to int32 class ids.
 * Returns the step loss, or a negative value on error. */
double ffc_model_fit_step(ffc_model_t *model, const double *x,
                          const int64_t *x_shape, int32_t x_ndims,
                          const double *y, const int64_t *y_shape,
                          int32_t y_ndims, int32_t y_is_labels);

/* Forward pass; flattens the first model output into `out` (float64).
 * Returns elements written (-1 on error/capacity); out_shape/out_ndims
 * (in: capacity of out_shape; out: rank) receive the output shape. */
int64_t ffc_model_predict(ffc_model_t *model, const double *x,
                          const int64_t *x_shape, int32_t x_ndims,
                          double *out, int64_t out_capacity,
                          int64_t *out_shape, int32_t *out_ndims);

/* ------------------------------------------------------------------ *
 * Dataloader kernels (reference: SingleDataLoader's batched index
 * loads, python/flexflow_dataloader.cc).
 * ------------------------------------------------------------------ */

/* dst[i] = src[idx[i]] row gather; rows are row_bytes wide. Spreads the
 * copy across num_threads (<=0: hardware concurrency). 0 on success. */
int32_t ffc_batch_gather(const void *src, void *dst, const int64_t *idx,
                         int64_t n_rows, int64_t row_bytes,
                         int32_t num_threads);

/* Deterministic in-place Fisher-Yates shuffle (splitmix64 stream). */
void ffc_shuffle_indices(int64_t *idx, int64_t n, uint64_t seed);

#ifdef __cplusplus
}
#endif

#endif /* FFCORE_H */
